"""Action executors: where the consumer's work — and the TPU — happens.

Rebuild of the reference's processors (reference: processor.go:56-470).
The ordering contract is safety-critical (docs/Processor.md:24-28):

  1. store requests, sync the request store
  2. write + sync the WAL                        ← durability barrier
  3. network sends (self-sends loop back through Node.step)
  4. forward requests (read data from the store)
  5. hashing                                     ← order-free, the TPU path
  6. commits: apply batches to the Log; checkpoints snap it

The TpuProcessor coalesces every hash request in the actions batch into one
padded tensor and runs a single batched SHA-256 kernel launch (ops.sha256),
overlapping the device round trip with the persist+send phases — the
reference's work-pool slack (hashing is order-free) realized as accelerator
batching instead of goroutines.
"""

from __future__ import annotations

import functools
import threading
import time

from .. import pb
from ..core import actions as act
from ..core.preimage import host_digest
from ..obsv import hooks
from .reconfig import decode_reconfig_request, reconfig_kind


def _observed_phase(phase):
    """Wrap a processor phase with per-phase latency recording (and a
    trace span when a tracer is installed).  Spans use the executing
    thread's ident as tid so pool-lane phases land on distinct trace rows
    and stay well-nested."""

    def wrap(fn):
        @functools.wraps(fn)
        def inner(self, *args, **kwargs):
            if not hooks.enabled:
                return fn(self, *args, **kwargs)
            tracer = hooks.tracer
            start = time.perf_counter()
            try:
                if tracer is not None:
                    with tracer.span(
                        "proc." + phase,
                        cat="runtime",
                        tid=threading.get_ident() & 0xFFFF,
                    ):
                        return fn(self, *args, **kwargs)
                return fn(self, *args, **kwargs)
            finally:
                hooks.metrics.histogram(
                    "mirbft_proc_phase_seconds", phase=phase
                ).observe(time.perf_counter() - start)

        return inner

    return wrap


class Link:
    """The entire transport contract (reference: processor.go:23-25):
    fire-and-forget, unreliable by assumption, caller authenticates."""

    def send(self, dest: int, msg: pb.Msg) -> None:
        raise NotImplementedError


class Log:
    """The application: applies totally-ordered batches and snapshots."""

    def apply(self, q_entry: pb.QEntry) -> None:
        raise NotImplementedError

    def snap(self, network_config, clients_state) -> bytes:
        raise NotImplementedError


class SerialProcessor:
    def __init__(self, node, link: Link, app_log: Log, wal, request_store):
        self.node = node
        self.link = link
        self.app_log = app_log
        self.wal = wal
        self.request_store = request_store
        # Reconfiguration requests recognised at store time, keyed by ack,
        # drained into CheckpointResult.reconfigurations in commit order.
        # Written by the persist phase, read by the commit phase: in the
        # pipelined processor those are different stage threads, but
        # persist(N) always precedes commit(N) and CPython dict ops are
        # atomic, so no lock is needed.
        self._reconfig_payloads: dict = {}  # ack key -> [pb.Reconfiguration]
        self._pending_reconfigs: list = []  # committed, awaiting checkpoint
        # Restart seeding: StoreRequest actions are not re-emitted on WAL
        # replay, but committed batches above the durable checkpoint are
        # re-committed — their reconfigurations must be re-collected, and
        # the payloads are still in the store (reconfiguration acks are
        # deliberately never pruned; see _commit).  Only durable stores can
        # carry pre-boot state, so a store without `uncommitted` (in-memory
        # harness stubs) has nothing to seed.
        uncommitted = getattr(self.request_store, "uncommitted", None)
        if uncommitted is not None:
            uncommitted(self._seed_reconfig)

    @staticmethod
    def _ack_key(ack) -> tuple:
        return (ack.client_id, ack.req_no, bytes(ack.digest))

    def _seed_reconfig(self, ack, data: bytes | None = None) -> None:
        # FileRequestStore.uncommitted hands only the ack; the in-memory
        # stores hand (ack, data) — read on demand for the former.
        if data is None:
            data = self.request_store.get(ack)
        if data is None:
            return
        reconfigs = decode_reconfig_request(data)
        if reconfigs:
            self._reconfig_payloads[self._ack_key(ack)] = reconfigs

    # -- phases --------------------------------------------------------------

    def _persist_writes(self, actions: act.Actions) -> None:
        """Stores and WAL appends/truncates only — no fsyncs.  Split out
        so the pipelined processor can issue the writes and then wait on
        group-commit tickets instead of private fsyncs."""
        for fr in actions.store_requests:
            self.request_store.store(fr.request_ack, fr.request_data)
            self._seed_reconfig(fr.request_ack, fr.request_data)
        for write in actions.write_ahead:
            if write.truncate is not None:
                self.wal.truncate(write.truncate)
            else:
                self.wal.write(write.append.index, write.append.data)

    @_observed_phase("persist")
    def _persist(self, actions: act.Actions) -> None:
        self._persist_writes(actions)
        self.request_store.sync()
        self.wal.sync()

    @_observed_phase("transmit")
    def _transmit(self, actions: act.Actions) -> None:
        my_id = self.node.config.id
        for send in actions.sends:
            for replica in send.targets:
                if replica == my_id:
                    self.node.step(replica, send.msg)
                else:
                    self.link.send(replica, send.msg)
        for fwd in actions.forward_requests:
            data = self.request_store.get(fwd.request_ack)
            if data is None:
                continue  # already committed + pruned; nothing to forward
            msg = pb.Msg(
                type=pb.ForwardRequest(
                    request_ack=fwd.request_ack, request_data=data
                )
            )
            for replica in fwd.targets:
                if replica == my_id:
                    self.node.step(replica, msg)
                else:
                    self.link.send(replica, msg)

    @_observed_phase("hash")
    def _hash(self, actions: act.Actions) -> list:
        return [
            act.HashResult(digest=host_digest(hr.data), request=hr)
            for hr in actions.hashes
        ]

    @_observed_phase("commit")
    def _commit(self, actions: act.Actions, defer_prune: list | None = None) -> list:
        """Apply batches and snap checkpoints.  With ``defer_prune`` set,
        committed acks are collected there instead of pruned from the
        request store inline — the pooled processor prunes after its lanes
        join so a same-batch forward can still read the data."""
        checkpoints = []
        for commit in actions.commits:
            if commit.batch is not None:
                self.app_log.apply(commit.batch)
                if hooks.enabled:
                    hooks.milestone(
                        "seq.committed",
                        self.node.config.id,
                        commit.batch.seq_no,
                    )
                for ack in commit.batch.requests:
                    reconfigs = (
                        self._reconfig_payloads.get(self._ack_key(ack))
                        if self._reconfig_payloads
                        else None
                    )
                    if reconfigs is not None:
                        # Collect in commit order for the window's
                        # checkpoint.  The ack is deliberately NOT pruned:
                        # if we crash before the covering CEntry is
                        # durable, WAL replay re-commits this batch and
                        # the payload must still be in the store for the
                        # restart seeding to re-collect (a node that
                        # pruned would silently drop the reconfiguration
                        # and fork the config).
                        self._pending_reconfigs.extend(reconfigs)
                        if hooks.enabled:
                            for reconfig in reconfigs:
                                hooks.metrics.counter(
                                    "mirbft_reconfig_committed_total",
                                    kind=reconfig_kind(reconfig),
                                ).inc()
                        continue
                    if defer_prune is not None:
                        defer_prune.append(ack)
                    else:
                        self.request_store.commit(ack)
            else:
                value = self.app_log.snap(
                    commit.checkpoint.network_config,
                    commit.checkpoint.clients_state,
                )
                reconfigs, self._pending_reconfigs = (
                    self._pending_reconfigs,
                    [],
                )
                checkpoints.append(
                    act.CheckpointResult(
                        checkpoint=commit.checkpoint,
                        value=value,
                        reconfigurations=reconfigs,
                    )
                )
        return checkpoints

    def process(self, actions: act.Actions) -> act.ActionResults:
        self._persist(actions)
        self._transmit(actions)
        digests = self._hash(actions)
        checkpoints = self._commit(actions)
        return act.ActionResults(digests=digests, checkpoints=checkpoints)


class PoolProcessor(SerialProcessor):
    """Parallel executor lanes with the persist→send safety barrier
    (reference: ProcessorWorkPool, processor.go:183-470; barrier semantics
    docs/Processor.md:22-28):

        (persist → sends + forwards) ∥ hashes ∥ commits

    All lanes are joined before the results return.  The invariant that
    matters: nothing is *sent* until the WAL and request store are
    durable, while hashing and committing float free of that barrier —
    exactly the slack the reference's work pool exploits with goroutines,
    here realized with a small thread pool (and, in TpuPoolProcessor, with
    the accelerator absorbing the hash lane).

    Unlike the reference, forwards run *after* this batch's persists (in
    the transmit lane) rather than concurrently with them: a single
    accumulated actions batch can contain both the store and a forward of
    the same request, and reading the store before the persist lane wrote
    it would silently drop the forward until a tick-driven retry.
    """

    def __init__(self, node, link: Link, app_log: Log, wal, request_store):
        super().__init__(node, link, app_log, wal, request_store)
        import concurrent.futures

        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=3, thread_name_prefix=f"proc-{node.config.id}"
        )

    def _hash_lane(self, actions: act.Actions) -> list:
        return self._hash(actions)

    def _persist_transmit_lane(self, actions: act.Actions) -> None:
        self._persist(actions)
        self._transmit(actions)

    def process(self, actions: act.Actions) -> act.ActionResults:
        # Store prune is deferred past the lane join: the commit lane runs
        # concurrently with the transmit lane, and pruning an ack that this
        # same batch also forwards would make the forward read None.
        import concurrent.futures

        pruned: list = []
        futures = [
            self._pool.submit(self._persist_transmit_lane, actions),
            self._pool.submit(self._hash_lane, actions),
            self._pool.submit(self._commit, actions, pruned),
        ]
        # Join ALL lanes before propagating any failure: raising while a
        # sibling lane still mutates the WAL/store would hand the caller a
        # half-written state.  Whatever the commit lane managed to commit
        # is pruned even on the failure path, so acks don't leak.
        concurrent.futures.wait(futures)
        try:
            results = [f.result() for f in futures]
        finally:
            for ack in pruned:
                self.request_store.commit(ack)
        return act.ActionResults(digests=results[1], checkpoints=results[2])

    def close(self) -> None:
        self._pool.shutdown(wait=True)


class _DeviceHashMixin:
    """The accelerator hash path shared by TpuProcessor/TpuPoolProcessor:
    dispatch every hash request in the action batch as one bucketed kernel
    call, collect the digests later (JAX async dispatch runs the kernel
    while the host does other phases)."""

    # Below this many hash requests the device round trip isn't worth it.
    min_batch_for_device = 64

    def _dispatch_device(self, hashes: list):
        from ..ops.batching import pack_preimages
        from ..ops.sha256 import sha256_digest_words

        start = time.perf_counter() if hooks.enabled else 0.0
        packed = pack_preimages([b"".join(hr.data) for hr in hashes])
        words = sha256_digest_words(packed.blocks, packed.n_blocks)
        if hooks.enabled:
            hooks.record_flush(
                "hash", "device", len(hashes), time.perf_counter() - start
            )
        return words

    def _collect_device(self, hashes: list, words) -> list:
        import numpy as np

        start = time.perf_counter() if hooks.enabled else 0.0
        raw = np.asarray(words).astype(">u4").tobytes()
        if hooks.enabled:
            hooks.record_flush(
                "hash", "readback", len(hashes), time.perf_counter() - start
            )
        return [
            act.HashResult(digest=raw[32 * i : 32 * i + 32], request=hr)
            for i, hr in enumerate(hashes)
        ]


class TpuProcessor(_DeviceHashMixin, SerialProcessor):
    """SerialProcessor with the hash phase dispatched to the accelerator.

    All hash requests in the batch launch as one bucketed kernel call; the
    dispatch is issued *before* the persist/send phases so the device works
    while the host fsyncs, and the results are collected afterwards — the
    persist→send barrier is untouched because hashing feeds nothing but
    AddResults."""

    def process(self, actions: act.Actions) -> act.ActionResults:
        pending = None
        if len(actions.hashes) >= self.min_batch_for_device:
            pending = self._dispatch_device(actions.hashes)

        self._persist(actions)
        self._transmit(actions)

        if pending is not None:
            digests = self._collect_device(actions.hashes, pending)
        else:
            digests = self._hash(actions)

        checkpoints = self._commit(actions)
        return act.ActionResults(digests=digests, checkpoints=checkpoints)


class TpuPoolProcessor(_DeviceHashMixin, PoolProcessor):
    """PoolProcessor with the accelerator absorbing the hash lane: the
    kernel dispatch is issued on the calling thread before the lanes
    launch, so the device computes while the persist/send/commit lanes
    run; the hash lane then only collects the results."""

    def process(self, actions: act.Actions) -> act.ActionResults:
        self._pending_device = None
        if len(actions.hashes) >= self.min_batch_for_device:
            self._pending_device = self._dispatch_device(actions.hashes)
        return super().process(actions)

    def _hash_lane(self, actions: act.Actions) -> list:
        if self._pending_device is not None:
            return self._collect_device(actions.hashes, self._pending_device)
        return self._hash(actions)


class ProcessorClosed(Exception):
    """process() was called on a closed (or crashed) pipelined processor."""


class _PipelinedBatch:
    """One Actions batch in flight through the pipeline stages."""

    __slots__ = ("actions", "pending_device")

    def __init__(self, actions: act.Actions):
        self.actions = actions
        self.pending_device = None


class _PipelinedGroup:
    """A run of consecutive batches persisted under one ticket pair.

    The persist stage drains every batch waiting in its queue into one
    group: their writes are issued together and a single group-commit
    token per store covers all of them (tokens snapshot the store's
    requested-sync counter, so one token after the last write covers
    every earlier write).  Group size adapts to load — idle clusters get
    one-batch groups and minimum latency, saturated ones get large groups
    and maximum fsync amortization — and, crucially, it bounds pipeline
    latency: downstream stages handle a whole group per queue hop, so
    depth collapses instead of compounding."""

    __slots__ = ("batches", "rs_token", "wal_token")

    def __init__(self, batches: list):
        self.batches = batches
        self.rs_token = None
        self.wal_token = None


class PipelinedProcessor(SerialProcessor):
    """Overlapped stage pipeline over consecutive Actions batches.

    The serial ladder runs every batch's persist→transmit→hash→commit on
    one thread, so per-batch latency IS the throughput ceiling.  This
    executor decomposes the ordering contract into stages connected by
    bounded queues, so batch N+1's persist, batch N's transmit, and
    order-free hashes all proceed concurrently (docs/Processor.md has the
    stage graph):

        intake ─→ persist ─→ barrier ─→ transmit ─→ commit
           └────────→ hash ─────────────────────────────┘

    - **persist** drains every waiting batch into one adaptive group
      (_PipelinedGroup), issues their stores and WAL appends, and
      registers one group-commit ticket pair (storage.sync_token) for
      the lot instead of fsyncing privately — k in-flight batches
      coalesce into ~1 fsync, and group-per-hop handling keeps pipeline
      latency bounded under load.
    - **barrier** redeems both tickets.  This is the per-batch durability
      barrier: no send for batch N happens before batch N's reqstore AND
      WAL data are durable (a group ticket covers every batch in the
      group, so sends can only be *later* than the per-batch contract
      requires, never earlier).  The relative fsync order of the two
      files is NOT part of the contract (the OS writes back dirty pages
      in any order it likes even under the serial ladder); only
      both-before-send is.
    - **transmit** performs sends and forwards; **commit** applies
      batches, prunes, snaps checkpoints, and hands checkpoints to
      node.add_results — process() itself returns an empty
      ActionResults, results are delivered internally.
    - **hash** runs on a side pool from intake (the accelerator path in
      TpuPipelinedProcessor) and delivers digests to node.add_results
      the moment they are computed.  Hashing is order-free and feeds
      nothing but AddResults, and digests gate the protocol's next round
      trip — parking them behind the fsync-paced stages would put the
      whole pipeline depth on the consensus critical path.

    A stage failure (e.g. a dying disk surfacing through a group-commit
    ticket) parks the pipeline and re-raises from the next process()
    call, so consumer loops observe the crash exactly as they would the
    serial ladder's."""

    _QUEUE_DEPTH = 8
    # Cap on batches merged into one persist group: bounds the work a
    # single queue hop carries (and thus worst-case batch latency).
    _MAX_GROUP = 64

    def __init__(self, node, link: Link, app_log: Log, wal, request_store):
        super().__init__(node, link, app_log, wal, request_store)
        import concurrent.futures
        import queue as queue_mod

        self._queue_mod = queue_mod
        # Embedder seam: because results are delivered internally (the
        # consumer loop never sees digests/checkpoints), embedders that
        # capture checkpoints off ActionResults (state-transfer serving in
        # chaos/live.py and the test harnesses) set this callable; the
        # commit stage invokes it before node.add_results.
        self.on_results = None
        self._stop = threading.Event()
        self._mutex = threading.Lock()
        self._error: BaseException | None = None  # guarded-by: _mutex
        self._closed = False  # guarded-by: _mutex
        self._inflight = 0  # guarded-by: _mutex
        self._inflight_cv = threading.Condition(self._mutex)
        # BoundedQueue (obsv/bqueue.py) gives every stage hand-off the
        # uniform mirbft_queue_{depth,wait_seconds,saturated_total}
        # series; the names are shared across nodes in one process so
        # the label space stays budgeted.
        from ..obsv.bqueue import BoundedQueue

        self._persist_q = BoundedQueue("proc.persist", self._QUEUE_DEPTH)
        self._barrier_q = BoundedQueue("proc.barrier", self._QUEUE_DEPTH)
        self._transmit_q = BoundedQueue("proc.transmit", self._QUEUE_DEPTH)
        self._commit_q = BoundedQueue("proc.commit", self._QUEUE_DEPTH)
        self._hash_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1,
            thread_name_prefix=f"proc-pipe-hash-{node.config.id}",
        )
        self._stages = [
            self._spawn_stage("persist", self._persist_stage),
            self._spawn_stage("barrier", self._barrier_stage),
            self._spawn_stage("transmit", self._transmit_stage),
            self._spawn_stage("commit", self._commit_stage),
        ]

    # -- plumbing ------------------------------------------------------------

    def _spawn_stage(self, name: str, fn) -> threading.Thread:
        """The pipeline's single thread-creation point (lint rule W10 bans
        raw threading.Thread anywhere else in this module): wraps the
        stage body with first-error capture and pipeline park."""
        thread = threading.Thread(
            target=self._stage_main,
            args=(fn,),
            name=f"proc-pipe-{self.node.config.id}-{name}",
            daemon=True,
        )
        thread.start()
        return thread

    def _stage_main(self, fn) -> None:
        try:
            fn()
        except BaseException as err:
            with self._mutex:
                if self._error is None:
                    self._error = err
                self._inflight_cv.notify_all()
            self._stop.set()

    def _gauge(self, stage: str, q) -> None:
        if hooks.enabled:
            depth = q.qsize()
            hooks.metrics.gauge(
                "mirbft_proc_stage_queue_depth", stage=stage
            ).set(depth)
            if hooks.recorder is not None:
                hooks.recorder.record(
                    "resource",
                    "proc.queue_depth",
                    args={"stage": stage, "depth": depth},
                )

    def _q_put(self, q, stage: str, batch) -> None:
        """Blocking put with backpressure that stays responsive to stop:
        a full pipeline throttles intake, a dead one raises."""
        while True:
            with self._mutex:
                if self._error is not None:
                    raise self._error
            if self._stop.is_set():
                raise ProcessorClosed("pipeline stopped")
            try:
                q.put(batch, timeout=0.05)
                break
            except self._queue_mod.Full:
                continue
        self._gauge(stage, q)

    def _q_get(self, q, stage: str):
        """Blocking get; returns None once the pipeline is stopping and
        the queue has drained (stages exit on None)."""
        while True:
            try:
                batch = q.get(timeout=0.05)
            except self._queue_mod.Empty:
                if self._stop.is_set():
                    return None
                continue
            self._gauge(stage, q)
            return batch

    def _batch_done(self) -> None:
        with self._mutex:
            self._inflight -= 1
            self._inflight_cv.notify_all()

    # -- stages --------------------------------------------------------------

    @_observed_phase("persist")
    def _persist_group(self, group: _PipelinedGroup) -> None:
        store_requests = write_ahead = False
        for batch in group.batches:
            self._persist_writes(batch.actions)
            store_requests = store_requests or bool(
                batch.actions.store_requests
            )
            write_ahead = write_ahead or bool(batch.actions.write_ahead)
        if store_requests:
            group.rs_token = self.request_store.sync_token()
        if write_ahead:
            group.wal_token = self.wal.sync_token()

    def _persist_stage(self) -> None:
        while True:
            batch = self._q_get(self._persist_q, "persist")
            if batch is None:
                return
            batches = [batch]
            while len(batches) < self._MAX_GROUP:
                try:
                    batches.append(self._persist_q.get_nowait())
                except self._queue_mod.Empty:
                    break
            group = _PipelinedGroup(batches)
            self._persist_group(group)
            self._q_put(self._barrier_q, "barrier", group)

    @_observed_phase("sync_wait")
    def _await_durability(self, group: _PipelinedGroup) -> None:
        """The durability barrier: both group-commit tickets must be
        redeemed before any of the group's sends."""
        for store, token in (
            (self.request_store, group.rs_token),
            (self.wal, group.wal_token),
        ):
            if token is None:
                continue
            while not store.wait(token, timeout=0.1):
                if self._stop.is_set():
                    raise ProcessorClosed("pipeline stopped mid-sync")

    def _barrier_stage(self) -> None:
        while True:
            group = self._q_get(self._barrier_q, "barrier")
            if group is None:
                return
            self._await_durability(group)
            self._q_put(self._transmit_q, "transmit", group)

    def _transmit_stage(self) -> None:
        while True:
            group = self._q_get(self._transmit_q, "transmit")
            if group is None:
                return
            for batch in group.batches:
                self._transmit(batch.actions)
            self._q_put(self._commit_q, "commit", group)

    def _commit_stage(self) -> None:
        while True:
            group = self._q_get(self._commit_q, "commit")
            if group is None:
                return
            for batch in group.batches:
                try:
                    checkpoints = self._commit(batch.actions)
                    if checkpoints:
                        self._emit_results(
                            act.ActionResults(
                                digests=[], checkpoints=checkpoints
                            )
                        )
                finally:
                    self._batch_done()

    def _emit_results(self, results: act.ActionResults) -> None:
        callback = self.on_results
        if callback is not None:
            callback(results)
        from .node import NodeStopped

        try:
            self.node.add_results(results)
        except NodeStopped:
            pass  # teardown race: the node left first; results are moot

    # -- intake --------------------------------------------------------------

    def _hash_batch(self, batch: _PipelinedBatch) -> None:
        """Hash worker: compute and deliver immediately.  Digests gate
        the protocol's next round trip (preprepare -> prepare needs the
        batch digest), so they must not ride behind the fsync-paced
        stages — holding them to commit cadence inflates per-seq latency
        enough to trip suspect timeouts under load.  A hash failure (a
        dying accelerator backend) parks the pipeline like any stage
        error."""
        try:
            if batch.pending_device is not None:
                digests = self._collect_device(
                    batch.actions.hashes, batch.pending_device
                )
            else:
                digests = self._hash(batch.actions)
            if digests:
                self._emit_results(
                    act.ActionResults(digests=digests, checkpoints=[])
                )
        except BaseException as err:
            with self._mutex:
                if self._error is None:
                    self._error = err
                self._inflight_cv.notify_all()
            self._stop.set()
            raise

    def _maybe_dispatch(self, actions: act.Actions):
        """Device-dispatch seam; the TPU variant launches the kernel here
        so the accelerator works while the pipeline persists."""
        return None

    def process(self, actions: act.Actions) -> act.ActionResults:
        with self._mutex:
            if self._error is not None:
                raise self._error
            if self._closed:
                raise ProcessorClosed("processor closed")
            self._inflight += 1
        batch = _PipelinedBatch(actions)
        try:
            batch.pending_device = self._maybe_dispatch(actions)
            if actions.hashes:
                self._hash_pool.submit(self._hash_batch, batch)
            self._q_put(self._persist_q, "persist", batch)
        except BaseException:
            self._batch_done()
            raise
        # Digests (hash worker) and checkpoints (commit stage) are
        # delivered to node.add_results internally; the consumer loop has
        # nothing to forward.
        return act.ActionResults(digests=[], checkpoints=[])

    def close(self, wait: bool = True) -> None:
        with self._mutex:
            self._closed = True
        if wait:
            deadline = time.monotonic() + 30.0
            with self._inflight_cv:
                while (
                    self._inflight > 0
                    and self._error is None
                    and time.monotonic() < deadline
                ):
                    self._inflight_cv.wait(timeout=0.1)
        self._stop.set()
        for thread in self._stages:
            thread.join(timeout=5.0)
        self._hash_pool.shutdown(wait=wait)


class TpuPipelinedProcessor(_DeviceHashMixin, PipelinedProcessor):
    """PipelinedProcessor with the hash stage on the accelerator: the
    bucketed SHA-256 kernel launches at intake (async dispatch), computes
    while the persist/barrier/transmit stages run, and the hash worker
    only collects the result words."""

    def _maybe_dispatch(self, actions: act.Actions):
        if len(actions.hashes) >= self.min_batch_for_device:
            return self._dispatch_device(actions.hashes)
        return None


# Config.processor values -> executor classes (build_processor resolves).
PROCESSOR_KINDS = {
    "serial": SerialProcessor,
    "pool": PoolProcessor,
    "tpu": TpuProcessor,
    "tpu-pool": TpuPoolProcessor,
    "pipelined": PipelinedProcessor,
    "tpu-pipelined": TpuPipelinedProcessor,
}


def build_processor(node, link: Link, app_log: Log, wal, request_store, kind=None):
    """Construct the executor selected by ``kind`` (or, when None, by
    ``node.config.processor``) — the single wiring point for runtime
    embedders (chaos/live.py, bench.py)."""
    if kind is None:
        kind = getattr(node.config, "processor", "serial")
    cls = PROCESSOR_KINDS.get(kind)
    if cls is None:
        raise ValueError(
            f"unknown processor kind {kind!r}; choose from "
            f"{sorted(PROCESSOR_KINDS)}"
        )
    return cls(node, link, app_log, wal, request_store)
