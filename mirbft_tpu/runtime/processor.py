"""Action executors: where the consumer's work — and the TPU — happens.

Rebuild of the reference's processors (reference: processor.go:56-470).
The ordering contract is safety-critical (docs/Processor.md:24-28):

  1. store requests, sync the request store
  2. write + sync the WAL                        ← durability barrier
  3. network sends (self-sends loop back through Node.step)
  4. forward requests (read data from the store)
  5. hashing                                     ← order-free, the TPU path
  6. commits: apply batches to the Log; checkpoints snap it

The TpuProcessor coalesces every hash request in the actions batch into one
padded tensor and runs a single batched SHA-256 kernel launch (ops.sha256),
overlapping the device round trip with the persist+send phases — the
reference's work-pool slack (hashing is order-free) realized as accelerator
batching instead of goroutines.
"""

from __future__ import annotations

import functools
import threading
import time

from .. import pb
from ..core import actions as act
from ..core.preimage import host_digest
from ..obsv import hooks


def _observed_phase(phase):
    """Wrap a processor phase with per-phase latency recording (and a
    trace span when a tracer is installed).  Spans use the executing
    thread's ident as tid so pool-lane phases land on distinct trace rows
    and stay well-nested."""

    def wrap(fn):
        @functools.wraps(fn)
        def inner(self, *args, **kwargs):
            if not hooks.enabled:
                return fn(self, *args, **kwargs)
            tracer = hooks.tracer
            start = time.perf_counter()
            try:
                if tracer is not None:
                    with tracer.span(
                        "proc." + phase,
                        cat="runtime",
                        tid=threading.get_ident() & 0xFFFF,
                    ):
                        return fn(self, *args, **kwargs)
                return fn(self, *args, **kwargs)
            finally:
                hooks.metrics.histogram(
                    "mirbft_proc_phase_seconds", phase=phase
                ).observe(time.perf_counter() - start)

        return inner

    return wrap


class Link:
    """The entire transport contract (reference: processor.go:23-25):
    fire-and-forget, unreliable by assumption, caller authenticates."""

    def send(self, dest: int, msg: pb.Msg) -> None:
        raise NotImplementedError


class Log:
    """The application: applies totally-ordered batches and snapshots."""

    def apply(self, q_entry: pb.QEntry) -> None:
        raise NotImplementedError

    def snap(self, network_config, clients_state) -> bytes:
        raise NotImplementedError


class SerialProcessor:
    def __init__(self, node, link: Link, app_log: Log, wal, request_store):
        self.node = node
        self.link = link
        self.app_log = app_log
        self.wal = wal
        self.request_store = request_store

    # -- phases --------------------------------------------------------------

    @_observed_phase("persist")
    def _persist(self, actions: act.Actions) -> None:
        for fr in actions.store_requests:
            self.request_store.store(fr.request_ack, fr.request_data)
        self.request_store.sync()

        for write in actions.write_ahead:
            if write.truncate is not None:
                self.wal.truncate(write.truncate)
            else:
                self.wal.write(write.append.index, write.append.data)
        self.wal.sync()

    @_observed_phase("transmit")
    def _transmit(self, actions: act.Actions) -> None:
        my_id = self.node.config.id
        for send in actions.sends:
            for replica in send.targets:
                if replica == my_id:
                    self.node.step(replica, send.msg)
                else:
                    self.link.send(replica, send.msg)
        for fwd in actions.forward_requests:
            data = self.request_store.get(fwd.request_ack)
            if data is None:
                continue  # already committed + pruned; nothing to forward
            msg = pb.Msg(
                type=pb.ForwardRequest(
                    request_ack=fwd.request_ack, request_data=data
                )
            )
            for replica in fwd.targets:
                if replica == my_id:
                    self.node.step(replica, msg)
                else:
                    self.link.send(replica, msg)

    @_observed_phase("hash")
    def _hash(self, actions: act.Actions) -> list:
        return [
            act.HashResult(digest=host_digest(hr.data), request=hr)
            for hr in actions.hashes
        ]

    @_observed_phase("commit")
    def _commit(self, actions: act.Actions, defer_prune: list | None = None) -> list:
        """Apply batches and snap checkpoints.  With ``defer_prune`` set,
        committed acks are collected there instead of pruned from the
        request store inline — the pooled processor prunes after its lanes
        join so a same-batch forward can still read the data."""
        checkpoints = []
        for commit in actions.commits:
            if commit.batch is not None:
                self.app_log.apply(commit.batch)
                if hooks.enabled:
                    hooks.milestone(
                        "seq.committed",
                        self.node.config.id,
                        commit.batch.seq_no,
                    )
                for ack in commit.batch.requests:
                    if defer_prune is not None:
                        defer_prune.append(ack)
                    else:
                        self.request_store.commit(ack)
            else:
                value = self.app_log.snap(
                    commit.checkpoint.network_config,
                    commit.checkpoint.clients_state,
                )
                checkpoints.append(
                    act.CheckpointResult(
                        checkpoint=commit.checkpoint, value=value
                    )
                )
        return checkpoints

    def process(self, actions: act.Actions) -> act.ActionResults:
        self._persist(actions)
        self._transmit(actions)
        digests = self._hash(actions)
        checkpoints = self._commit(actions)
        return act.ActionResults(digests=digests, checkpoints=checkpoints)


class PoolProcessor(SerialProcessor):
    """Parallel executor lanes with the persist→send safety barrier
    (reference: ProcessorWorkPool, processor.go:183-470; barrier semantics
    docs/Processor.md:22-28):

        (persist → sends + forwards) ∥ hashes ∥ commits

    All lanes are joined before the results return.  The invariant that
    matters: nothing is *sent* until the WAL and request store are
    durable, while hashing and committing float free of that barrier —
    exactly the slack the reference's work pool exploits with goroutines,
    here realized with a small thread pool (and, in TpuPoolProcessor, with
    the accelerator absorbing the hash lane).

    Unlike the reference, forwards run *after* this batch's persists (in
    the transmit lane) rather than concurrently with them: a single
    accumulated actions batch can contain both the store and a forward of
    the same request, and reading the store before the persist lane wrote
    it would silently drop the forward until a tick-driven retry.
    """

    def __init__(self, node, link: Link, app_log: Log, wal, request_store):
        super().__init__(node, link, app_log, wal, request_store)
        import concurrent.futures

        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=3, thread_name_prefix=f"proc-{node.config.id}"
        )

    def _hash_lane(self, actions: act.Actions) -> list:
        return self._hash(actions)

    def _persist_transmit_lane(self, actions: act.Actions) -> None:
        self._persist(actions)
        self._transmit(actions)

    def process(self, actions: act.Actions) -> act.ActionResults:
        # Store prune is deferred past the lane join: the commit lane runs
        # concurrently with the transmit lane, and pruning an ack that this
        # same batch also forwards would make the forward read None.
        import concurrent.futures

        pruned: list = []
        futures = [
            self._pool.submit(self._persist_transmit_lane, actions),
            self._pool.submit(self._hash_lane, actions),
            self._pool.submit(self._commit, actions, pruned),
        ]
        # Join ALL lanes before propagating any failure: raising while a
        # sibling lane still mutates the WAL/store would hand the caller a
        # half-written state.  Whatever the commit lane managed to commit
        # is pruned even on the failure path, so acks don't leak.
        concurrent.futures.wait(futures)
        try:
            results = [f.result() for f in futures]
        finally:
            for ack in pruned:
                self.request_store.commit(ack)
        return act.ActionResults(digests=results[1], checkpoints=results[2])

    def close(self) -> None:
        self._pool.shutdown(wait=True)


class _DeviceHashMixin:
    """The accelerator hash path shared by TpuProcessor/TpuPoolProcessor:
    dispatch every hash request in the action batch as one bucketed kernel
    call, collect the digests later (JAX async dispatch runs the kernel
    while the host does other phases)."""

    # Below this many hash requests the device round trip isn't worth it.
    min_batch_for_device = 64

    def _dispatch_device(self, hashes: list):
        from ..ops.batching import pack_preimages
        from ..ops.sha256 import sha256_digest_words

        start = time.perf_counter() if hooks.enabled else 0.0
        packed = pack_preimages([b"".join(hr.data) for hr in hashes])
        words = sha256_digest_words(packed.blocks, packed.n_blocks)
        if hooks.enabled:
            hooks.record_flush(
                "hash", "device", len(hashes), time.perf_counter() - start
            )
        return words

    def _collect_device(self, hashes: list, words) -> list:
        import numpy as np

        start = time.perf_counter() if hooks.enabled else 0.0
        raw = np.asarray(words).astype(">u4").tobytes()
        if hooks.enabled:
            hooks.record_flush(
                "hash", "readback", len(hashes), time.perf_counter() - start
            )
        return [
            act.HashResult(digest=raw[32 * i : 32 * i + 32], request=hr)
            for i, hr in enumerate(hashes)
        ]


class TpuProcessor(_DeviceHashMixin, SerialProcessor):
    """SerialProcessor with the hash phase dispatched to the accelerator.

    All hash requests in the batch launch as one bucketed kernel call; the
    dispatch is issued *before* the persist/send phases so the device works
    while the host fsyncs, and the results are collected afterwards — the
    persist→send barrier is untouched because hashing feeds nothing but
    AddResults."""

    def process(self, actions: act.Actions) -> act.ActionResults:
        pending = None
        if len(actions.hashes) >= self.min_batch_for_device:
            pending = self._dispatch_device(actions.hashes)

        self._persist(actions)
        self._transmit(actions)

        if pending is not None:
            digests = self._collect_device(actions.hashes, pending)
        else:
            digests = self._hash(actions)

        checkpoints = self._commit(actions)
        return act.ActionResults(digests=digests, checkpoints=checkpoints)


class TpuPoolProcessor(_DeviceHashMixin, PoolProcessor):
    """PoolProcessor with the accelerator absorbing the hash lane: the
    kernel dispatch is issued on the calling thread before the lanes
    launch, so the device computes while the persist/send/commit lanes
    run; the hash lane then only collects the results."""

    def process(self, actions: act.Actions) -> act.ActionResults:
        self._pending_device = None
        if len(actions.hashes) >= self.min_batch_for_device:
            self._pending_device = self._dispatch_device(actions.hashes)
        return super().process(actions)

    def _hash_lane(self, actions: act.Actions) -> list:
        if self._pending_device is not None:
            return self._collect_device(actions.hashes, self._pending_device)
        return self._hash(actions)
