"""Action executors: where the consumer's work — and the TPU — happens.

Rebuild of the reference's processors (reference: processor.go:56-470).
The ordering contract is safety-critical (docs/Processor.md:24-28):

  1. store requests, sync the request store
  2. write + sync the WAL                        ← durability barrier
  3. network sends (self-sends loop back through Node.step)
  4. forward requests (read data from the store)
  5. hashing                                     ← order-free, the TPU path
  6. commits: apply batches to the Log; checkpoints snap it

The TpuProcessor coalesces every hash request in the actions batch into one
padded tensor and runs a single batched SHA-256 kernel launch (ops.sha256),
overlapping the device round trip with the persist+send phases — the
reference's work-pool slack (hashing is order-free) realized as accelerator
batching instead of goroutines.
"""

from __future__ import annotations

from .. import pb
from ..core import actions as act
from ..core.preimage import host_digest


class Link:
    """The entire transport contract (reference: processor.go:23-25):
    fire-and-forget, unreliable by assumption, caller authenticates."""

    def send(self, dest: int, msg: pb.Msg) -> None:
        raise NotImplementedError


class Log:
    """The application: applies totally-ordered batches and snapshots."""

    def apply(self, q_entry: pb.QEntry) -> None:
        raise NotImplementedError

    def snap(self, network_config, clients_state) -> bytes:
        raise NotImplementedError


class SerialProcessor:
    def __init__(self, node, link: Link, app_log: Log, wal, request_store):
        self.node = node
        self.link = link
        self.app_log = app_log
        self.wal = wal
        self.request_store = request_store

    # -- phases --------------------------------------------------------------

    def _persist(self, actions: act.Actions) -> None:
        for fr in actions.store_requests:
            self.request_store.store(fr.request_ack, fr.request_data)
        self.request_store.sync()

        for write in actions.write_ahead:
            if write.truncate is not None:
                self.wal.truncate(write.truncate)
            else:
                self.wal.write(write.append.index, write.append.data)
        self.wal.sync()

    def _transmit(self, actions: act.Actions) -> None:
        my_id = self.node.config.id
        for send in actions.sends:
            for replica in send.targets:
                if replica == my_id:
                    self.node.step(replica, send.msg)
                else:
                    self.link.send(replica, send.msg)
        for fwd in actions.forward_requests:
            data = self.request_store.get(fwd.request_ack)
            if data is None:
                continue  # already committed + pruned; nothing to forward
            msg = pb.Msg(
                type=pb.ForwardRequest(
                    request_ack=fwd.request_ack, request_data=data
                )
            )
            for replica in fwd.targets:
                if replica == my_id:
                    self.node.step(replica, msg)
                else:
                    self.link.send(replica, msg)

    def _hash(self, actions: act.Actions) -> list:
        return [
            act.HashResult(digest=host_digest(hr.data), request=hr)
            for hr in actions.hashes
        ]

    def _commit(self, actions: act.Actions) -> list:
        checkpoints = []
        for commit in actions.commits:
            if commit.batch is not None:
                self.app_log.apply(commit.batch)
                for ack in commit.batch.requests:
                    self.request_store.commit(ack)
            else:
                value = self.app_log.snap(
                    commit.checkpoint.network_config,
                    commit.checkpoint.clients_state,
                )
                checkpoints.append(
                    act.CheckpointResult(
                        checkpoint=commit.checkpoint, value=value
                    )
                )
        return checkpoints

    def process(self, actions: act.Actions) -> act.ActionResults:
        self._persist(actions)
        self._transmit(actions)
        digests = self._hash(actions)
        checkpoints = self._commit(actions)
        return act.ActionResults(digests=digests, checkpoints=checkpoints)


class TpuProcessor(SerialProcessor):
    """SerialProcessor with the hash phase dispatched to the accelerator.

    All hash requests in the batch launch as one bucketed kernel call; the
    dispatch is issued *before* the persist/send phases so the device works
    while the host fsyncs, and the results are collected afterwards — the
    persist→send barrier is untouched because hashing feeds nothing but
    AddResults."""

    # Below this many hash requests the device round trip isn't worth it.
    min_batch_for_device = 64

    def process(self, actions: act.Actions) -> act.ActionResults:
        pending = None
        if len(actions.hashes) >= self.min_batch_for_device:
            pending = self._dispatch_device(actions.hashes)

        self._persist(actions)
        self._transmit(actions)

        if pending is not None:
            digests = self._collect_device(actions.hashes, pending)
        else:
            digests = self._hash(actions)

        checkpoints = self._commit(actions)
        return act.ActionResults(digests=digests, checkpoints=checkpoints)

    def _dispatch_device(self, hashes: list):
        from ..ops.batching import pack_preimages
        from ..ops.sha256 import sha256_digest_words

        packed = pack_preimages([b"".join(hr.data) for hr in hashes])
        return sha256_digest_words(packed.blocks, packed.n_blocks)

    def _collect_device(self, hashes: list, words) -> list:
        import numpy as np

        raw = np.asarray(words).astype(">u4").tobytes()
        return [
            act.HashResult(digest=raw[32 * i : 32 * i + 32], request=hr)
            for i, hr in enumerate(hashes)
        ]
