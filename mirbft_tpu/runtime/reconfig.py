"""Reconfiguration requests on the wire, and the one shared
checkpoint-state helper.

Mir-BFT orders configuration changes like any other request
(arXiv:1906.05552 §IV): a client submits an opaque payload, the batch
commits through the normal broadcast path, and the *application* layer
recognises it as a reconfiguration and hands it back to the protocol via
``CheckpointResult.reconfigurations`` — to be applied atomically at the
next stable checkpoint (``core.commitstate.next_network_config``).

This module owns the payload format (a magic prefix so the commit path
can recognise reconfiguration requests with one ``startswith`` and zero
extra I/O) and ``checkpoint_network_state`` — the single place a runtime
embedder turns a ``CheckpointResult`` into the ``pb.NetworkState`` it
stamps on snapshots and checkpoint records.  Every embedder (cluster
worker, loadgen in-process replica, live chaos replica) must build that
state here so none can drop ``pending_reconfigurations`` and fork the
adoption path.
"""

from __future__ import annotations

import struct

from .. import pb

# One magic byte sequence in front of the encoded payload.  A leading
# NUL keeps it out of the printable keyspace the KV app and the load
# generators use, so ordinary application payloads can never collide.
RECONFIG_MAGIC = b"\x00mirbft-reconfig/1\x00"

_LEN = struct.Struct(">I")


def reconfig_kind(reconfig: pb.Reconfiguration) -> str:
    """The metrics/label name for a reconfiguration arm."""
    change = reconfig.type
    if isinstance(change, pb.ReconfigNewClient):
        return "new_client"
    if isinstance(change, pb.ReconfigRemoveClient):
        return "remove_client"
    if isinstance(change, pb.NetworkConfig):
        return "network_config"
    return "unknown"


def encode_reconfig_request(reconfigs) -> bytes:
    """Serialize an ordered list of ``pb.Reconfiguration`` into a request
    payload: magic prefix, then length-prefixed encoded entries."""
    parts = [RECONFIG_MAGIC]
    for reconfig in reconfigs:
        body = pb.encode(reconfig)
        parts.append(_LEN.pack(len(body)))
        parts.append(body)
    return b"".join(parts)


def is_reconfig_request(data: bytes) -> bool:
    return data.startswith(RECONFIG_MAGIC)


def decode_reconfig_request(data: bytes):
    """The reconfigurations carried by a request payload, or ``None`` if
    the payload is not a reconfiguration request.  A payload that carries
    the magic but is malformed decodes to an empty list — the request
    still committed everywhere in the same order, so every correct node
    must draw the same (empty) conclusion from it rather than crash."""
    if not data.startswith(RECONFIG_MAGIC):
        return None
    out = []
    offset = len(RECONFIG_MAGIC)
    try:
        while offset < len(data):
            (length,) = _LEN.unpack_from(data, offset)
            offset += _LEN.size
            body = data[offset : offset + length]
            if len(body) != length:
                return []
            offset += length
            out.append(pb.decode(pb.Reconfiguration, body))
    except Exception:  # noqa: BLE001 — malformed is a same-everywhere no-op
        return []
    return out


def checkpoint_network_state(cr) -> pb.NetworkState:
    """The ``pb.NetworkState`` for a runtime ``CheckpointResult`` —
    config and client set from the checkpoint request, plus the
    reconfigurations that committed inside the window (the part the
    embedders used to hand-copy, and one of them would eventually have
    dropped)."""
    return pb.NetworkState(
        config=cr.checkpoint.network_config,
        clients=cr.checkpoint.clients_state,
        pending_reconfigurations=list(cr.reconfigurations),
    )
