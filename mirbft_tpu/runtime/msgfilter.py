"""Structural preflight validation of inbound wire messages.

Rebuild of the reference's preProcess (reference: msgfilter.go:18-105),
run in the *caller's* thread by Node.step before the message enters the
serializer.  The codec already rejects unset oneofs on decode; this guards
required nested fields for messages constructed in-process or decoded from
peers, and bounds the variable-size fields so a flooding peer cannot ship
arbitrarily large Preprepares, payloads, or digests past ingress.
"""

from __future__ import annotations

from .. import pb

# Fallback bounds when the caller passes no Config (testengine paths).
# Generous relative to honest traffic: batches are batch_size acks (cut
# smaller on heartbeats), payloads are application requests, digests are
# sha256 (32 bytes) — honest messages sit far below all three.
_DEFAULT_MAX_BATCH_ACKS = 256
_DEFAULT_MAX_REQUEST_BYTES = 1024 * 1024
_DEFAULT_MAX_DIGEST_BYTES = 64
_DEFAULT_MAX_SNAPSHOT_CHUNK_BYTES = 256 * 1024
_DEFAULT_MAX_SNAPSHOT_BYTES = 64 * 1024 * 1024


class MalformedMessage(ValueError):
    """Preflight rejection.  ``kind`` labels the failure for the
    ``mirbft_byzantine_rejections_total`` taxonomy: ``malformed``
    (structural), ``oversized_batch``, ``oversized_payload``,
    ``oversized_digest``, ``oversized_snapshot_chunk`` (state-transfer
    ingress, see check_snapshot_chunk), or ``bad_mac`` (a replica-plane
    frame whose link MAC failed, see check_frame_mac)."""

    def __init__(self, message: str, kind: str = "malformed"):
        super().__init__(message)
        self.kind = kind


def check_frame_mac(link_auth, peer: int, payload: bytes):
    """MAC ingress check for a replica-plane transport frame.

    ``link_auth`` is the node's crypto/mac.LinkAuthenticator, ``peer``
    the claimed sender (which selects the link key — a forged claim
    fails the tag like any other tamper).  Returns ``(verified_payload,
    None)`` with the tag stripped, or ``(None, kind)`` naming the
    rejection: ``short_frame`` (too short to even carry a tag) or
    ``bad_mac`` (tag present but wrong).  The transport counts the kind
    into ``mirbft_mac_rejections_total``; callers that prefer the
    exception taxonomy can raise ``MalformedMessage(..., kind=kind)``.
    """
    from ..crypto.mac import TAG_LEN

    if len(payload) <= TAG_LEN:
        return None, "short_frame"
    body = link_auth.open(peer, payload)
    if body is None:
        return None, "bad_mac"
    return body, None


def _check_digest(digest: bytes, limit: int, what: str) -> None:
    if len(digest) > limit:
        raise MalformedMessage(
            f"{what} digest is {len(digest)} bytes (max {limit})",
            kind="oversized_digest",
        )


def _check_acks(acks, max_acks: int, max_digest: int, what: str) -> None:
    if len(acks) > max_acks:
        raise MalformedMessage(
            f"{what} carries {len(acks)} acks (max {max_acks})",
            kind="oversized_batch",
        )
    for ack in acks:
        _check_digest(ack.digest, max_digest, f"{what} ack")


def check_snapshot_chunk(
    payload_len: int, total_chunks: int, limits=None
) -> None:
    """Ingress bound for state-transfer chunk frames (which are not
    pb.Msg and so bypass pre_process): reject any chunk whose payload
    exceeds the per-chunk cap, and any chunk count that would let the
    full reassembly exceed the snapshot cap — a byzantine donor must not
    be able to OOM a fetcher with one huge chunk or a chunk flood."""
    max_chunk = getattr(
        limits, "max_snapshot_chunk_bytes", _DEFAULT_MAX_SNAPSHOT_CHUNK_BYTES
    )
    max_total = getattr(
        limits, "max_snapshot_bytes", _DEFAULT_MAX_SNAPSHOT_BYTES
    )
    if payload_len > max_chunk:
        raise MalformedMessage(
            f"snapshot chunk is {payload_len} bytes (max {max_chunk})",
            kind="oversized_snapshot_chunk",
        )
    if total_chunks < 1 or total_chunks * max_chunk > max_total:
        raise MalformedMessage(
            f"snapshot of {total_chunks} chunks may exceed "
            f"{max_total} bytes",
            kind="oversized_snapshot_chunk",
        )


def pre_process(msg: pb.Msg, limits=None) -> None:
    """Validate structure and size bounds.  ``limits`` is a runtime
    ``Config`` (or any object with ``max_batch_acks`` /
    ``max_request_bytes`` / ``max_digest_bytes``); omitted attributes
    fall back to the module defaults."""
    max_acks = getattr(limits, "max_batch_acks", _DEFAULT_MAX_BATCH_ACKS)
    max_payload = getattr(
        limits, "max_request_bytes", _DEFAULT_MAX_REQUEST_BYTES
    )
    max_digest = getattr(
        limits, "max_digest_bytes", _DEFAULT_MAX_DIGEST_BYTES
    )
    inner = msg.type
    if inner is None:
        raise MalformedMessage("message has no type set")
    if isinstance(inner, pb.ForwardRequest):
        if inner.request_ack is None:
            raise MalformedMessage("ForwardRequest without request_ack")
        _check_digest(
            inner.request_ack.digest, max_digest, "ForwardRequest"
        )
        if len(inner.request_data) > max_payload:
            raise MalformedMessage(
                f"ForwardRequest payload is {len(inner.request_data)} "
                f"bytes (max {max_payload})",
                kind="oversized_payload",
            )
    elif isinstance(inner, pb.Preprepare):
        _check_acks(inner.batch, max_acks, max_digest, "Preprepare")
    elif isinstance(inner, pb.ForwardBatch):
        _check_acks(
            inner.request_acks, max_acks, max_digest, "ForwardBatch"
        )
        _check_digest(inner.digest, max_digest, "ForwardBatch")
    elif isinstance(
        inner, (pb.Prepare, pb.Commit, pb.FetchBatch, pb.RequestAck, pb.FetchRequest)
    ):
        _check_digest(inner.digest, max_digest, type(inner).__name__)
    elif isinstance(inner, pb.NewEpoch):
        cfg = inner.new_config
        if cfg is None:
            raise MalformedMessage("NewEpoch without new_config")
        if cfg.config is None:
            raise MalformedMessage("NewEpoch without new_config.config")
        if cfg.starting_checkpoint is None:
            raise MalformedMessage("NewEpoch without starting_checkpoint")
    elif isinstance(inner, (pb.NewEpochEcho, pb.NewEpochReady)):
        cfg = inner.new_epoch_config
        if cfg is None:
            raise MalformedMessage(
                f"{type(inner).__name__} without new_epoch_config"
            )
        if cfg.config is None or cfg.starting_checkpoint is None:
            raise MalformedMessage(
                f"{type(inner).__name__} config incomplete"
            )
    elif isinstance(inner, pb.EpochChangeAck):
        if inner.epoch_change is None:
            raise MalformedMessage("EpochChangeAck without epoch_change")
    elif not isinstance(
        inner, (pb.Suspect, pb.Checkpoint, pb.EpochChange)
    ):
        raise MalformedMessage(f"unknown message type {type(inner).__name__}")
