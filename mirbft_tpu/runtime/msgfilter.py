"""Structural preflight validation of inbound wire messages.

Rebuild of the reference's preProcess (reference: msgfilter.go:18-105),
run in the *caller's* thread by Node.step before the message enters the
serializer.  The codec already rejects unset oneofs on decode; this guards
required nested fields for messages constructed in-process or decoded from
peers.
"""

from __future__ import annotations

from .. import pb


class MalformedMessage(ValueError):
    pass


def pre_process(msg: pb.Msg) -> None:
    inner = msg.type
    if inner is None:
        raise MalformedMessage("message has no type set")
    if isinstance(inner, pb.ForwardRequest):
        if inner.request_ack is None:
            raise MalformedMessage("ForwardRequest without request_ack")
    elif isinstance(inner, pb.NewEpoch):
        cfg = inner.new_config
        if cfg is None:
            raise MalformedMessage("NewEpoch without new_config")
        if cfg.config is None:
            raise MalformedMessage("NewEpoch without new_config.config")
        if cfg.starting_checkpoint is None:
            raise MalformedMessage("NewEpoch without starting_checkpoint")
    elif isinstance(inner, (pb.NewEpochEcho, pb.NewEpochReady)):
        cfg = inner.new_epoch_config
        if cfg is None:
            raise MalformedMessage(
                f"{type(inner).__name__} without new_epoch_config"
            )
        if cfg.config is None or cfg.starting_checkpoint is None:
            raise MalformedMessage(
                f"{type(inner).__name__} config incomplete"
            )
    elif isinstance(inner, pb.EpochChangeAck):
        if inner.epoch_change is None:
            raise MalformedMessage("EpochChangeAck without epoch_change")
    elif not isinstance(
        inner,
        (
            pb.Preprepare,
            pb.Prepare,
            pb.Commit,
            pb.Suspect,
            pb.Checkpoint,
            pb.RequestAck,
            pb.FetchRequest,
            pb.FetchBatch,
            pb.ForwardBatch,
            pb.EpochChange,
        ),
    ):
        raise MalformedMessage(f"unknown message type {type(inner).__name__}")
