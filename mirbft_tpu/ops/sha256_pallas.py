"""SHA-256 as a Pallas TPU kernel.

The XLA scan kernel (ops/sha256.py) pays for generality: the message
schedule's rolling window is re-materialized every scan step and the round
sequence lives in a scan whose carries bounce through VMEM.  This kernel
lays the problem out the way the VPU wants it:

- the **batch** fills full (8, 128) VPU tiles — 1024 messages per grid
  program, each word of each message a distinct (sublane, lane) slot, so
  every round is a full-width (8×128) vector operation (a one-sublane
  layout measured ~4x slower: 7/8 of the VPU idle);
- the 64 rounds and the schedule are **fully unrolled** inside the kernel
  (the window is a Python list of (8, 128) slabs — no copies, no carries);
- the block loop is a `fori_loop` with per-message freezing once its
  block count is exhausted.

Inputs are padded/transposed *inside the jit* (no host round trip, so the
async-dispatch pipeline of testengine/crypto_plane.py stays async) to
(blocks, 16, batch/128, 128); each program's BlockSpec is a contiguous
(blocks, 16, 8, 128) slab.  Two cases fall back to the XLA kernel on the
real-TPU path: block buckets too large for a VMEM-resident slab, and
batches below one tile (where padding to 1024 rows would waste 4x+ the
compute).  Measured honestly (chained compressions, scalar readback,
distinct inputs): ~3.7x the XLA scan kernel on the same chip.

uint32 has no native TPU lowering for some ops, so words are carried as
int32 with wrap-around adds (two's complement ≡ mod 2^32) and *logical*
right shifts via lax.shift_right_logical.

Bit-exactness vs hashlib is gated in tests/test_sha256.py (interpret mode
on every run; Mosaic via the MIRBFT_TPU_TPU_TESTS-gated test and the
bench's built-in assertion).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..obsv import device as _device
from .sha256 import _IV, _K

LANES = 128
SUBLANES = 8
TILE = SUBLANES * LANES  # messages per grid program: a full (8, 128) VPU tile
# Beyond this block bucket one program's input slab (max_blocks x 64 KiB)
# no longer fits comfortably in VMEM (~16 MiB) alongside the working set.
MAX_PALLAS_BLOCKS = 64


def _rotr(x, n: int):
    right = jax.lax.shift_right_logical(x, jnp.int32(n))
    left = jax.lax.shift_left(x, jnp.int32(32 - n))
    return right | left


def _shr(x, n: int):
    return jax.lax.shift_right_logical(x, jnp.int32(n))


def _compress(state, w):
    """One fully-unrolled SHA-256 compression: state is a tuple of 8
    (8, 128) int32 slabs, w a list of 16 message-word slabs.  Shared by
    the digest and benchmark kernels so they cannot drift apart."""
    k = [int(v) for v in _K.astype(np.int32)]
    w = list(w)
    for t in range(16, 64):
        w15, w2 = w[t - 15], w[t - 2]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ _shr(w15, 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ _shr(w2, 10)
        w.append(w[t - 16] + s0 + w[t - 7] + s1)

    a, b, c, d, e, f, g, h = state
    for t in range(64):
        big_s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + big_s1 + ch + jnp.int32(k[t]) + w[t]
        big_s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        a, b, c, d, e, f, g, h = (
            t1 + big_s0 + maj, a, b, c, d + t1, e, f, g,
        )
    return tuple(
        old + new for old, new in zip(state, (a, b, c, d, e, f, g, h))
    )


def _initial_state():
    # Constants enter as Python ints (Pallas kernels cannot close over
    # traced arrays).
    iv = [int(v) for v in _IV.astype(np.int32)]
    return tuple(
        jnp.full((SUBLANES, LANES), iv[i], dtype=jnp.int32)
        for i in range(8)
    )


def _kernel(blocks_ref, n_blocks_ref, out_ref, *, max_blocks: int):
    """blocks_ref: (max_blocks, 16, 8, 128) int32; n_blocks_ref:
    (1, 8, 128) int32; out_ref: (8, 8, 128) int32."""
    live_counts = n_blocks_ref[0, :, :]

    def block_body(j, state):
        w = [blocks_ref[j, i, :, :] for i in range(16)]
        new_state = _compress(state, w)
        live = j < live_counts
        return tuple(
            jnp.where(live, new, old)
            for old, new in zip(state, new_state)
        )

    state = jax.lax.fori_loop(0, max_blocks, block_body, _initial_state())
    for i in range(8):
        out_ref[i, :, :] = state[i]


def _chain_kernel(block_ref, out_ref, *, iters: int):
    """Benchmark kernel: ``iters`` chained compressions over one block per
    message (same measurement protocol as ops.sha256.sha256_chain_checksum)."""
    w0 = [block_ref[i, :, :] for i in range(16)]

    def body(_, state):
        return _compress(state, w0)

    state = jax.lax.fori_loop(0, iters, body, _initial_state())
    for i in range(8):
        out_ref[i, :, :] = state[i]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _digest_device(blocks, n_blocks, *, interpret: bool):
    """blocks: (batch, max_blocks, 16) uint32/int32; n_blocks: (batch,)
    int32.  Padding, transposition, and un-padding all run on device."""
    batch, max_blocks, _ = blocks.shape
    padded = -(-batch // TILE) * TILE
    blocks_p = jnp.pad(
        blocks.astype(jnp.int32), ((0, padded - batch), (0, 0), (0, 0))
    )
    counts = jnp.pad(n_blocks.astype(jnp.int32), (0, padded - batch))
    blocks_t = jnp.moveaxis(blocks_p, 0, 2).reshape(
        max_blocks, 16, padded // LANES, LANES
    )
    words = pl.pallas_call(
        functools.partial(_kernel, max_blocks=max_blocks),
        out_shape=jax.ShapeDtypeStruct(
            (8, padded // LANES, LANES), jnp.int32
        ),
        grid=(padded // TILE,),
        in_specs=[
            pl.BlockSpec(
                (max_blocks, 16, SUBLANES, LANES), lambda i: (0, 0, i, 0)
            ),
            pl.BlockSpec((1, SUBLANES, LANES), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((8, SUBLANES, LANES), lambda i: (0, i, 0)),
        interpret=interpret,
    )(blocks_t, counts.reshape(1, padded // LANES, LANES))
    flat = jnp.moveaxis(words.reshape(8, padded), 0, 1)
    return flat[:batch].astype(jnp.uint32)


@functools.partial(jax.jit, static_argnames=("iters", "interpret"))
def sha256_chain_checksum_pallas(block, *, iters: int, interpret: bool = False):
    """block: (batch, 16) int32/uint32 -> scalar uint32 checksum after
    ``iters`` chained compressions per message (batch multiple of TILE)."""
    batch = block.shape[0]
    block_t = jnp.moveaxis(block.astype(jnp.int32), 0, 1).reshape(
        16, batch // LANES, LANES
    )
    words = pl.pallas_call(
        functools.partial(_chain_kernel, iters=iters),
        out_shape=jax.ShapeDtypeStruct((8, batch // LANES, LANES), jnp.int32),
        grid=(batch // TILE,),
        in_specs=[pl.BlockSpec((16, SUBLANES, LANES), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((8, SUBLANES, LANES), lambda i: (0, i, 0)),
        interpret=interpret,
    )(block_t)
    return jnp.sum(words.astype(jnp.uint32), dtype=jnp.uint32)


# sync=False for the same reason as ops.sha256.sha256_chain_checksum: the
# chain microbench syncs via scalar readback only.
sha256_chain_checksum_pallas = _device.instrument(
    "sha256_chain_pallas", sync=False
)(sha256_chain_checksum_pallas)


@_device.instrument("sha256_digest_pallas")
def sha256_digest_words_pallas(blocks, n_blocks, interpret: bool | None = None):
    """Drop-in for ops.sha256.sha256_digest_words: blocks (batch,
    max_blocks, 16) uint32, n_blocks (batch,) int32 -> (batch, 8) uint32.

    On non-TPU backends the Pallas interpreter is used unless overridden.
    On the real-TPU path, oversized block buckets (VMEM) and sub-tile
    batches (padding waste) fall back to the XLA kernel."""
    if interpret is None:
        # Where will this actually run?  jax_default_device (pinned to CPU
        # by the test suite) wins over the default backend.
        dev = jax.config.jax_default_device
        platform = dev.platform if dev is not None else jax.default_backend()
        interpret = platform != "tpu"
    batch, max_blocks, _ = np.shape(blocks)
    if not interpret and (max_blocks > MAX_PALLAS_BLOCKS or batch < TILE):
        from .sha256 import sha256_digest_words

        return sha256_digest_words(jnp.asarray(blocks), jnp.asarray(n_blocks))
    return _digest_device(
        jnp.asarray(blocks), jnp.asarray(n_blocks), interpret=interpret
    )
