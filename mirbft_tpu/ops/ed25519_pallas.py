"""Ed25519 verification as a Pallas TPU kernel.

The XLA scan ladder (ops/ed25519.py) runs every field operation as a
(batch,)-wide op: 20-limb carry chains become hundreds of tiny vector ops
and the schoolbook product leans on an int32 dot_general the MXU has no
good tiling for.  This kernel applies the same full-tile treatment that
bought 3.4x on SHA-256 (ops/sha256_pallas.py):

- the **batch** fills full VPU tiles (default (16, 128) — 2048 signatures
  per grid program); a field element is a Python list of 20 int32 slabs,
  so every limb operation is a full-width vector op;
- the double-scalar multiplication is a **4-bit windowed Shamir ladder**:
  64 `fori_loop` iterations of 4 dedicated doublings (dbl-2008-hwcd,
  4 squarings + 4 products) plus one constant-table add for [S]B (the 16
  multiples of B baked in as Python-int limb constants) and one
  variable-table add for [k](-A) (16 multiples built in-kernel, selected
  by a 4-level where tree);
- squarings use the symmetric schoolbook (210 products vs 400).

Field arithmetic is the proven 20x13-bit limb schoolbook of
ops/ed25519.py, mirrored slab-for-limb (same magnitudes, same 3-pass
carry, same 2^260 = 608 fold), so the int32 exactness argument carries
over unchanged.  Bit-exactness against crypto/ed25519_host.py is gated in
tests/test_ed25519.py on the valid/corrupted/invalid corpus.

**Device-side decompression** (_decompress_kernel): the host marshalling
of ops/ed25519.py spends ~250µs per signature in bigint modular
exponentiation decompressing A and R — at ladder-kernel speeds that host
work, not the device, caps throughput.  Here the candidate square root
x = uv^3 (uv^7)^((p-5)/8) runs on device via the ref10 pow22523 addition
chain (252 squarings + 11 multiplications) over the same slab field ops,
so the host keeps only byte parsing, range checks, and the SHA-512
challenge (verify_batch_pallas / marshal_light).  Measured end-to-end:
~20k verifies/s sustained at chunk=4096 on one chip — ~15x the XLA
scan ladder of round 3.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..crypto import ed25519_host as host
from ..obsv import device as _device
from .ed25519 import FOLD, MASK, NLIMB, RADIX, int_to_limbs

LANES = 128
SUBLANES = 8
TILE = SUBLANES * LANES

# Curve constants as Python int lists (Pallas kernels close over Python
# scalars, never traced arrays).
_D2_L = [int(v) for v in int_to_limbs((2 * host.D) % host.P)]
_D_L = [int(v) for v in int_to_limbs(host.D % host.P)]
_BX_L = [int(v) for v in int_to_limbs(host.BASE[0])]
_BY_L = [int(v) for v in int_to_limbs(host.BASE[1])]
_BT_L = [int(v) for v in int_to_limbs(host.BASE[0] * host.BASE[1] % host.P)]
# sqrt(-1) mod p, used to fix up the candidate root in decompression.
_SQRT_M1 = pow(2, (host.P - 1) // 4, host.P)
_SQRT_M1_L = [int(v) for v in int_to_limbs(_SQRT_M1)]


def _const(value_limbs, shape):
    return [jnp.full(shape, v, dtype=jnp.int32) for v in value_limbs]


def _zero(shape):
    return [jnp.zeros(shape, dtype=jnp.int32) for _ in range(NLIMB)]


def _one(shape):
    return _const([1] + [0] * (NLIMB - 1), shape)


# -- slab field arithmetic (mirrors ops/ed25519.py bounds exactly) ----------


def _carry20(x):
    """One carry pass over 20 limb slabs with the 2^260 -> 608 fold."""
    out = []
    carry = None
    for i in range(NLIMB):
        v = x[i] if carry is None else x[i] + carry
        out.append(v & MASK)
        carry = v >> RADIX
    out[0] = out[0] + carry * FOLD
    return out


def _carry(x):
    """Three passes, as in ops/ed25519.py._carry (nlimb=20)."""
    for _ in range(3):
        x = _carry20(x)
    return x


def _carry_prod(cols):
    """Carry 39 product columns down to 20 limbs (the nlimb>NLIMB branch
    of ops/ed25519.py._carry): one pass over 39 producing a 40th carry
    limb, fold limbs 20..39 back via 608, then two more 20-limb passes."""
    out = []
    carry = None
    for i in range(2 * NLIMB - 1):
        v = cols[i] if carry is None else cols[i] + carry
        out.append(v & MASK)
        carry = v >> RADIX
    out.append(carry)
    lo = out[:NLIMB]
    hi = out[NLIMB:]  # exactly NLIMB entries (19 high columns + top carry)
    lo = [l + h * FOLD for l, h in zip(lo, hi)]
    for _ in range(2):
        lo = _carry20(lo)
    return lo


def _mul(a, b):
    """Schoolbook 20x20 -> 39 columns -> carried 20 limbs.  Exact in int32
    by the bounds proven in ops/ed25519.py (13-bit limbs, 20-term sums)."""
    cols = [None] * (2 * NLIMB - 1)
    for i in range(NLIMB):
        ai = a[i]
        for j in range(NLIMB):
            p = ai * b[j]
            c = i + j
            cols[c] = p if cols[c] is None else cols[c] + p
    return _carry_prod(cols)


def _sqr(a):
    """Squaring via the symmetric schoolbook: 210 distinct products (the
    i<j cross terms counted twice via a cheap add) instead of 400 — int32
    multiplies are the expensive VPU op in this kernel.  Bounds: identical
    column sums to _mul(a, a)."""
    cols = [None] * (2 * NLIMB - 1)
    for i in range(NLIMB):
        ai = a[i]
        sq = ai * ai
        cols[2 * i] = sq if cols[2 * i] is None else cols[2 * i] + sq
        for j in range(i + 1, NLIMB):
            p = ai * a[j]
            p = p + p
            c = i + j
            cols[c] = p if cols[c] is None else cols[c] + p
    return _carry_prod(cols)


def _add(a, b):
    return _carry([x + y for x, y in zip(a, b)])


def _sub(a, b):
    return _carry([x - y for x, y in zip(a, b)])


def _point_add(p, q, d2):
    """Unified extended twisted-Edwards addition (add-2008-hwcd-3),
    slab-for-limb identical to ops/ed25519.py._point_add."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = _mul(_sub(y1, x1), _sub(y2, x2))
    b = _mul(_add(y1, x1), _add(y2, x2))
    c = _mul(_mul(t1, t2), d2)
    d = _mul(z1, z2)
    d = _add(d, d)
    e = _sub(b, a)
    f = _sub(d, c)
    g = _add(d, c)
    h = _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _canonical(x):
    """Carried limb slabs -> the unique representative in [0, p)."""
    hi = x[NLIMB - 1] >> 8
    x = list(x)
    x[NLIMB - 1] = x[NLIMB - 1] & 255
    x[0] = x[0] + hi * 19
    x = _carry(x)
    for _ in range(2):
        t = list(x)
        t[0] = t[0] + 19
        t = _carry(t)
        ge = (t[NLIMB - 1] >> 8) > 0
        t[NLIMB - 1] = t[NLIMB - 1] & 255
        x = [jnp.where(ge, tv, xv) for tv, xv in zip(t, x)]
    return x


def _feq(a, b):
    ca = _canonical(a)
    cb = _canonical(b)
    eq = None
    for va, vb in zip(ca, cb):
        e = va == vb
        eq = e if eq is None else (eq & e)
    return eq


def _select(bit, point, other):
    cond = bit != 0
    return tuple(
        [jnp.where(cond, pc, oc) for pc, oc in zip(pcs, ocs)]
        for pcs, ocs in zip(point, other)
    )


def _point_double(p):
    """Dedicated extended-coordinates doubling (dbl-2008-hwcd, a=-1):
    4 squarings + 4 products — one multiply fewer than the unified add,
    and no d2 constant."""
    x1, y1, z1, _t1 = p
    a = _sqr(x1)
    b = _sqr(y1)
    zz = _sqr(z1)
    c = _add(zz, zz)
    t = _add(x1, y1)
    e = _sub(_sub(_sqr(t), a), b)
    g = _sub(b, a)  # D + B with D = -A
    f = _sub(g, c)
    h = _sub(_zero(a[0].shape), _add(a, b))  # H = -A - B
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _select16_var(w, table):
    """Branchless 16-way select from a variable point table via a 4-level
    where tree (15 point-selects)."""
    b0 = (w & 1) != 0
    b1 = (w & 2) != 0
    b2 = (w & 4) != 0
    b3 = (w & 8) != 0

    def sel(cond, p, q):
        return tuple(
            [jnp.where(cond, pc, qc) for pc, qc in zip(pcs, qcs)]
            for pcs, qcs in zip(p, q)
        )

    l1 = [sel(b0, table[2 * i + 1], table[2 * i]) for i in range(8)]
    l2 = [sel(b1, l1[2 * i + 1], l1[2 * i]) for i in range(4)]
    l3 = [sel(b2, l2[2 * i + 1], l2[2 * i]) for i in range(2)]
    return sel(b3, l3[1], l3[0])


# [j]B for j = 0..15 in extended coordinates, as Python int limb lists
# (j = 0 is the identity).  Baked at import from the host reference.
def _b_table_consts():
    table = []
    for j in range(16):
        if j == 0:
            table.append(
                (
                    [0] * NLIMB,
                    [1] + [0] * (NLIMB - 1),
                    [1] + [0] * (NLIMB - 1),
                    [0] * NLIMB,
                )
            )
            continue
        pt = host.scalar_mult(j, host.to_extended(host.BASE))
        z_inv = pow(pt[2], host.P - 2, host.P)
        x = pt[0] * z_inv % host.P
        y = pt[1] * z_inv % host.P
        table.append(
            (
                [int(v) for v in int_to_limbs(x)],
                [int(v) for v in int_to_limbs(y)],
                [1] + [0] * (NLIMB - 1),
                [int(v) for v in int_to_limbs(x * y % host.P)],
            )
        )
    return table


_B_TABLE = _b_table_consts()


def _select16_const(w, shape):
    """16-way select from the constant [j]B table: one-hot masks times
    Python-int limb constants (the compiler folds the constant products)."""
    masks = [(w == j).astype(jnp.int32) for j in range(16)]
    out = []
    for coord in range(4):
        limbs = []
        for i in range(NLIMB):
            acc = None
            for j in range(16):
                c = _B_TABLE[j][coord][i]
                if c == 0:
                    continue
                term = masks[j] * c
                acc = term if acc is None else acc + term
            limbs.append(
                acc
                if acc is not None
                else jnp.zeros(shape, dtype=jnp.int32)
            )
        out.append(limbs)
    return tuple(out)


# -- the ladder kernel -------------------------------------------------------


def _ladder_tail(swin_ref, kwin_ref, neg_a, rx, ry, shape):
    """The shared windowed-Shamir body: [S]B + [k](-A) compared
    projectively against affine R; returns the (s, l) bool validity slab.

    Per window: 4 dedicated doublings + a constant-table add for the base
    point + a variable-table add for -A — versus the bit-serial form's
    4 unified doublings + 8 conditional unified adds."""
    d2 = _const(_D2_L, shape)
    identity = (_zero(shape), _one(shape), _one(shape), _zero(shape))

    # [j](-A) for j = 0..15: 14 unified additions, amortized over the 64
    # windows.
    a_table = [identity, neg_a]
    for _ in range(14):
        a_table.append(_point_add(a_table[-1], neg_a, d2))

    def step(t, acc):
        sw = swin_ref[t, :, :]
        kw = kwin_ref[t, :, :]
        for _ in range(4):
            acc = _point_double(acc)
        acc = _point_add(acc, _select16_const(sw, shape), d2)
        acc = _point_add(acc, _select16_var(kw, a_table), d2)
        return acc

    acc = jax.lax.fori_loop(0, 64, step, identity)

    x, y, z, _t = acc
    ok = _feq(x, _mul(rx, z)) & _feq(y, _mul(ry, z))
    nonzero = jnp.logical_not(_feq(z, _zero(shape)))
    return ok & nonzero


def _ladder_kernel(
    swin_ref, kwin_ref, na_ref, r_ref, out_ref, *, shape
):
    """swin_ref/kwin_ref: (64, s, l) int32 windows (values 0..15,
    MSB-first).  na_ref: (4, 20, s, l) extended coords of -A.
    r_ref: (2, 20, s, l) affine R.  out_ref: (1, s, l) int32."""
    neg_a = tuple(
        [na_ref[c, i, :, :] for i in range(NLIMB)] for c in range(4)
    )
    rx = [r_ref[0, i, :, :] for i in range(NLIMB)]
    ry = [r_ref[1, i, :, :] for i in range(NLIMB)]
    ok = _ladder_tail(swin_ref, kwin_ref, neg_a, rx, ry, shape)
    out_ref[0, :, :] = ok.astype(jnp.int32)


def _ladder_affine_kernel(
    swin_ref, kwin_ref, a_ref, r_ref, valid_ref, out_ref, *, shape
):
    """Ladder over device-decompressed points: a_ref/r_ref are
    (2, 20, s, l) *affine* A and R (from _decompress_kernel); valid_ref is
    the (1, s, l) conjunction of both decompressions' ok flags.  -A's
    extended coordinates are built in-kernel (one negation + one mul)."""
    ax = [a_ref[0, i, :, :] for i in range(NLIMB)]
    ay = [a_ref[1, i, :, :] for i in range(NLIMB)]
    nx = _sub(_zero(shape), ax)
    neg_a = (nx, ay, _one(shape), _mul(nx, ay))
    rx = [r_ref[0, i, :, :] for i in range(NLIMB)]
    ry = [r_ref[1, i, :, :] for i in range(NLIMB)]
    ok = _ladder_tail(swin_ref, kwin_ref, neg_a, rx, ry, shape)
    out_ref[0, :, :] = (ok & (valid_ref[0, :, :] != 0)).astype(jnp.int32)


# -- device-side point decompression ----------------------------------------


def _pow22523(z):
    """z^((p-5)/8) = z^(2^252 - 3) via the standard ref10 addition chain:
    252 squarings + 11 multiplications (vs ~125 multiplications for plain
    square-and-multiply over the 250-bit exponent)."""

    def sqn(x, n):
        for _ in range(n):
            x = _sqr(x)
        return x

    t0 = _sqr(z)  # 2
    t1 = sqn(t0, 2)  # 8
    t1 = _mul(z, t1)  # 9
    t0 = _mul(t0, t1)  # 11
    t0 = _sqr(t0)  # 22
    t0 = _mul(t1, t0)  # 31 = 2^5 - 1
    t1 = sqn(t0, 5)
    t0 = _mul(t1, t0)  # 2^10 - 1
    t1 = sqn(t0, 10)
    t1 = _mul(t1, t0)  # 2^20 - 1
    t2 = sqn(t1, 20)
    t1 = _mul(t2, t1)  # 2^40 - 1
    t1 = sqn(t1, 10)
    t0 = _mul(t1, t0)  # 2^50 - 1
    t1 = sqn(t0, 50)
    t1 = _mul(t1, t0)  # 2^100 - 1
    t2 = sqn(t1, 100)
    t1 = _mul(t2, t1)  # 2^200 - 1
    t1 = sqn(t1, 50)
    t0 = _mul(t1, t0)  # 2^250 - 1
    t0 = sqn(t0, 2)
    return _mul(t0, z)  # 2^252 - 3


def _decompress_kernel(y_ref, sign_ref, out_ref, ok_ref, *, shape):
    """RFC 8032 §5.1.3 point decompression on device.

    y_ref: (20, s, l) candidate y limbs (already reduced mod 2^255 by the
    host byte parse; the host also rejects y >= p).  sign_ref: (1, s, l)
    requested x parity.  out_ref: (2, 20, s, l) affine (x, y).
    ok_ref: (1, s, l) 1 when the encoding is a curve point."""
    y = [y_ref[i, :, :] for i in range(NLIMB)]
    sign = sign_ref[0, :, :]

    one = _one(shape)
    d = _const(_D_L, shape)
    yy = _sqr(y)
    u = _sub(yy, one)  # y^2 - 1
    v = _add(_mul(d, yy), one)  # d y^2 + 1

    v2 = _sqr(v)
    v3 = _mul(v2, v)
    v7 = _mul(_sqr(v3), v)
    pow_arg = _mul(u, v7)
    root = _pow22523(pow_arg)
    x = _mul(_mul(u, v3), root)  # candidate root of u/v

    vxx = _mul(v, _sqr(x))
    neg_u = _sub(_zero(shape), u)
    is_root = _feq(vxx, u)
    is_neg_root = _feq(vxx, neg_u)
    sqrt_m1 = _const(_SQRT_M1_L, shape)
    x_fixed = _mul(x, sqrt_m1)
    x = [jnp.where(is_neg_root, xf, xv) for xf, xv in zip(x_fixed, x)]
    ok = is_root | is_neg_root

    # Parity fix-up: x = -x when the canonical parity mismatches the sign
    # bit; x == 0 with sign 1 is invalid (RFC 8032 step 4).
    xc = _canonical(x)
    parity = xc[0] & 1
    x_is_zero = _feq(x, _zero(shape))
    flip = parity != sign
    x_neg = _sub(_zero(shape), x)
    x = [jnp.where(flip, nv, xv) for nv, xv in zip(x_neg, x)]
    ok = ok & jnp.logical_not(x_is_zero & (sign != 0))

    for i in range(NLIMB):
        out_ref[0, i, :, :] = x[i]
        out_ref[1, i, :, :] = y[i]
    ok_ref[0, :, :] = ok.astype(jnp.int32)


# -- the full verify pipeline ------------------------------------------------


def _limbs_from_bytes(arr: np.ndarray) -> np.ndarray:
    """(n, 32) little-endian uint8 -> (n, 20) int32 13-bit limbs, with
    bit 255 cleared (the sign bit is extracted separately)."""
    bits = np.unpackbits(arr, axis=1, bitorder="little").astype(np.int32)
    bits[:, 255] = 0
    bits = np.pad(bits, ((0, 0), (0, NLIMB * RADIX - 256)))
    weights = (1 << np.arange(RADIX, dtype=np.int32))
    return bits.reshape(-1, NLIMB, RADIX) @ weights


def _windows_from_bytes(arr: np.ndarray) -> np.ndarray:
    """(n, 32) little-endian uint8 scalars -> (n, 64) int32 4-bit windows,
    MSB-first (window 0 = bits 255..252)."""
    high = arr >> 4
    low = arr & 15
    inter = np.stack([high, low], axis=2)  # (n, 32, 2): per byte [hi, lo]
    return inter[:, ::-1, :].reshape(-1, 64).astype(np.int32)


@functools.partial(
    jax.jit, static_argnames=("interpret", "sublanes", "lanes")
)
def _verify_device(
    y_a,
    sign_a,
    y_r,
    sign_r,
    s_wins,
    k_wins,
    *,
    interpret: bool = False,
    sublanes: int = SUBLANES,
    lanes: int = LANES,
):
    """Decompress A and R (one batched kernel over 2n rows) and run the
    affine ladder, all on device — the host contributes only byte parsing,
    the SHA-512 challenge, and window extraction.

    y_a/y_r: (n, 20) int32 y limbs (bit 255 cleared, host-checked < p);
    sign_a/sign_r: (n,) int32; s_wins/k_wins: (n, 64) int32.
    Returns (n,) bool."""
    n = y_a.shape[0]
    tile = sublanes * lanes
    padded = -(-n // tile) * tile

    def tile_limbs20(limbs, rows):
        p = jnp.pad(limbs.astype(jnp.int32), ((0, rows - limbs.shape[0]), (0, 0)))
        return jnp.moveaxis(p, 0, 1).reshape(NLIMB, rows // lanes, lanes)

    # One decompression launch for both point columns: rows [0, padded) are
    # A, rows [padded, 2*padded) are R — each half is tile-aligned so a
    # grid program never straddles the two.
    y_both = jnp.concatenate(
        [
            jnp.pad(y_a.astype(jnp.int32), ((0, padded - n), (0, 0))),
            jnp.pad(y_r.astype(jnp.int32), ((0, padded - n), (0, 0))),
        ]
    )
    s_both = jnp.concatenate(
        [
            jnp.pad(sign_a.astype(jnp.int32), (0, padded - n)),
            jnp.pad(sign_r.astype(jnp.int32), (0, padded - n)),
        ]
    )
    y_t = jnp.moveaxis(y_both, 0, 1).reshape(NLIMB, 2 * padded // lanes, lanes)
    s_t = s_both.reshape(1, 2 * padded // lanes, lanes)

    xy, ok = pl.pallas_call(
        functools.partial(_decompress_kernel, shape=(sublanes, lanes)),
        out_shape=(
            jax.ShapeDtypeStruct(
                (2, NLIMB, 2 * padded // lanes, lanes), jnp.int32
            ),
            jax.ShapeDtypeStruct((1, 2 * padded // lanes, lanes), jnp.int32),
        ),
        grid=(2 * padded // tile,),
        in_specs=[
            pl.BlockSpec((NLIMB, sublanes, lanes), lambda i: (0, i, 0)),
            pl.BlockSpec((1, sublanes, lanes), lambda i: (0, i, 0)),
        ],
        out_specs=(
            pl.BlockSpec(
                (2, NLIMB, sublanes, lanes), lambda i: (0, 0, i, 0)
            ),
            pl.BlockSpec((1, sublanes, lanes), lambda i: (0, i, 0)),
        ),
        interpret=interpret,
    )(y_t, s_t)

    half = padded // lanes
    a_xy = xy[:, :, :half, :]
    r_xy = xy[:, :, half:, :]
    valid = (ok[:, :half, :] != 0) & (ok[:, half:, :] != 0)

    def tile_wins(wins):
        p = jnp.pad(wins.astype(jnp.int32), ((0, padded - n), (0, 0)))
        return jnp.moveaxis(p, 0, 1).reshape(64, half, lanes)

    out = pl.pallas_call(
        functools.partial(_ladder_affine_kernel, shape=(sublanes, lanes)),
        out_shape=jax.ShapeDtypeStruct((1, half, lanes), jnp.int32),
        grid=(padded // tile,),
        in_specs=[
            pl.BlockSpec((64, sublanes, lanes), lambda i: (0, i, 0)),
            pl.BlockSpec((64, sublanes, lanes), lambda i: (0, i, 0)),
            pl.BlockSpec((2, NLIMB, sublanes, lanes), lambda i: (0, 0, i, 0)),
            pl.BlockSpec((2, NLIMB, sublanes, lanes), lambda i: (0, 0, i, 0)),
            pl.BlockSpec((1, sublanes, lanes), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, sublanes, lanes), lambda i: (0, i, 0)),
        interpret=interpret,
    )(
        tile_wins(s_wins),
        tile_wins(k_wins),
        a_xy,
        r_xy,
        valid.astype(jnp.int32),
    )
    return out.reshape(padded)[:n] != 0


def marshal_light(pk: bytes, message: bytes, signature: bytes):
    """Host-side preparation for the full device pipeline: byte parsing,
    range checks, and the SHA-512 challenge — no bigint exponentiation
    (decompression runs on device).  Returns (pk32, r32, s_int, k_int) or
    None when structurally invalid."""
    import hashlib

    if len(pk) != 32 or len(signature) != 64:
        return None
    y_a = int.from_bytes(pk, "little") & ((1 << 255) - 1)
    y_r = int.from_bytes(signature[:32], "little") & ((1 << 255) - 1)
    if y_a >= host.P or y_r >= host.P:
        return None
    s = int.from_bytes(signature[32:], "little")
    if s >= host.L:
        return None
    k = (
        int.from_bytes(
            hashlib.sha512(signature[:32] + pk + message).digest(), "little"
        )
        % host.L
    )
    return (pk, signature[:32], s, k)


def launch_rows(rows: list, sublanes: int = 16):
    """Dispatch marshalled rows (from ``marshal_light``) to the device
    verify pipeline and return the in-flight device array WITHOUT forcing
    it — the caller polls/forces later (``np.asarray(out)[:len(rows)]``),
    so device compute and the D2H copy overlap host work.

    Rows pad to a power-of-two bucket (min one tile) by replicating row 0
    so only O(log(chunk/tile)) shapes ever reach the compiler — the
    full-ladder Mosaic compile is expensive and must not rerun for every
    residual tail length.  Padding rows' results are discarded."""
    from .batching import next_pow2

    if not rows:
        raise ValueError("launch_rows requires at least one marshalled row")
    tile = sublanes * LANES
    bucket = next_pow2(len(rows), floor=tile)
    padded_rows = rows + [rows[0]] * (bucket - len(rows))
    pk_arr = np.frombuffer(
        b"".join(r[0] for r in padded_rows), dtype=np.uint8
    ).reshape(-1, 32)
    r_arr = np.frombuffer(
        b"".join(r[1] for r in padded_rows), dtype=np.uint8
    ).reshape(-1, 32)
    s_arr = np.frombuffer(
        b"".join(r[2].to_bytes(32, "little") for r in padded_rows),
        dtype=np.uint8,
    ).reshape(-1, 32)
    k_arr = np.frombuffer(
        b"".join(r[3].to_bytes(32, "little") for r in padded_rows),
        dtype=np.uint8,
    ).reshape(-1, 32)
    out = _verify_device(
        _limbs_from_bytes(pk_arr),
        (pk_arr[:, 31] >> 7).astype(np.int32),
        _limbs_from_bytes(r_arr),
        (r_arr[:, 31] >> 7).astype(np.int32),
        _windows_from_bytes(s_arr),
        _windows_from_bytes(k_arr),
        sublanes=sublanes,
    )
    try:
        out.copy_to_host_async()
    except (AttributeError, RuntimeError):
        pass  # non-jax arrays (tests) or backends without async D2H
    return out


@_device.instrument("ed25519_verify_pallas")
def verify_batch_pallas(
    pks: list,
    messages: list,
    signatures: list,
    chunk: int = 4096,
    sublanes: int = 16,
) -> np.ndarray:
    """Full-pipeline batched verification; returns (n,) bool.

    Structural failures reject on the host; everything else — both point
    decompressions and the windowed Shamir ladder — runs on device in
    fixed-shape chunks launched as marshalling proceeds, so host SHA-512 /
    parsing overlaps device compute (same pipelining as
    ops.ed25519.verify_batch)."""
    n = len(pks)
    assert len(messages) == n and len(signatures) == n
    ok = np.zeros(n, dtype=bool)
    pending = []
    rows: list = []
    indices: list = []

    def launch():
        nonlocal rows, indices
        if not rows:
            return
        pending.append((indices, launch_rows(rows, sublanes=sublanes)))
        rows, indices = [], []

    for i, (pk, msg, sig) in enumerate(zip(pks, messages, signatures)):
        row = marshal_light(pk, msg, sig)
        if row is None:
            continue
        rows.append(row)
        indices.append(i)
        if len(rows) == chunk:
            launch()
    launch()

    for idx, out in pending:
        valid = np.asarray(out)
        for i, v in zip(idx, valid[: len(idx)]):
            ok[i] = bool(v)
    return ok
