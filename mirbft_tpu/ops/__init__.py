"""TPU compute plane: batched crypto kernels behind the Actions→Results seam.

The reference's hot path is serial host hashing (reference:
processor.go:133-143, `h := Hasher(); h.Write(...)`).  Here that compute is
coalesced across action batches into fixed-shape arrays and dispatched to
jit/vmap JAX kernels that XLA vectorizes over the TPU's VPU lanes, with
bucketed padding to avoid recompilation storms.
"""

from .sha256 import sha256, sha256_many  # noqa: F401
from .batching import PreimageBatch, pack_preimages  # noqa: F401
