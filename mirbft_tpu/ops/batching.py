"""Host-side packing of ragged preimages into fixed-shape kernel inputs.

The hard part of putting consensus crypto on an accelerator is that hash
preimages are variable-length while XLA wants static shapes (SURVEY hard
part #3).  Strategy: pad every message with standard SHA-256 padding, round
the block axis and the batch axis up to power-of-two buckets, and zero-fill
the remainder.  Only O(log(max_len) * log(max_batch)) distinct shapes ever
reach the compiler, so there are no recompilation storms; padded rows cost
compute but not correctness (their block count is 0, so their lanes just
carry the IV through the scan).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np


def next_pow2(n: int, floor: int = 1) -> int:
    v = max(n, floor)
    return 1 << (v - 1).bit_length()


def sha256_pad(message: bytes) -> bytes:
    """FIPS 180-4 padding: 0x80, zeros, 64-bit big-endian bit length."""
    bit_len = len(message) * 8
    padded = message + b"\x80"
    padded += b"\x00" * ((56 - len(padded)) % 64)
    return padded + struct.pack(">Q", bit_len)


@dataclass
class PreimageBatch:
    blocks: np.ndarray  # (batch, max_blocks, 16) uint32 big-endian words
    n_blocks: np.ndarray  # (batch,) int32


def pack_preimages(
    messages: list,
    block_floor: int = 1,
    batch_floor: int = 8,
) -> PreimageBatch:
    """Pack byte strings into a bucketed, padded uint32 block tensor.

    The batch axis is rounded to a power of two and then up to a multiple of
    ``batch_floor`` — callers sharding over an n-device mesh pass
    batch_floor=n so shard_map's even-split requirement holds for any mesh
    size, not just powers of two."""
    padded = [sha256_pad(m) for m in messages]
    counts = [len(p) // 64 for p in padded]

    max_blocks = next_pow2(max(counts), block_floor)
    batch = next_pow2(len(messages))
    batch += (-batch) % batch_floor

    # One join + one frombuffer instead of a numpy row-assignment per
    # message — the packing runs on the engine's critical path at every
    # crypto-plane launch.
    row_bytes = max_blocks * 64
    zero = bytes(row_bytes)
    parts = []
    append = parts.append
    for p in padded:
        append(p)
        if len(p) != row_bytes:
            append(zero[: row_bytes - len(p)])
    tail_rows = batch - len(messages)
    if tail_rows:
        append(bytes(row_bytes * tail_rows))
    buf = np.frombuffer(b"".join(parts), dtype=np.uint8).reshape(
        batch, row_bytes
    )

    blocks = (
        buf.view(">u4")
        .astype(np.uint32)
        .reshape(batch, max_blocks, 16)
    )
    n_blocks = np.zeros(batch, dtype=np.int32)
    n_blocks[: len(counts)] = counts

    return PreimageBatch(blocks=blocks, n_blocks=n_blocks)
