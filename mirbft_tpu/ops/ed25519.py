"""Batched Ed25519 signature verification as a JAX kernel.

BASELINE ladder rung 3: client requests are Ed25519-signed and replicas
verify them in batch on the accelerator behind the same Actions→Results
seam as digesting (reference leaves authentication to the consumer,
mirbft.go:297-301 — this is the consumer, TPU-native).

Work split (each side does what it is good at):

- **Host** (crypto/ed25519_host.py bigints + hashlib): parse/validate the
  encodings, decompress the two curve points (A, R), compute the SHA-512
  challenge k = H(R‖A‖M) mod L, and emit the scalar *bits* and point
  *limbs*.  All cheap, branchy, variable-length work.
- **Device**: the expensive part — a 256-step Shamir double-scalar ladder
  computing [S]B + [k](−A) in extended twisted-Edwards coordinates, then a
  projective comparison against R.  Everything is fixed-shape batched
  int32 arithmetic: no data-dependent control flow, the batch dimension
  rides the VPU lanes, the sequential 256 steps live in one lax.scan.

Field arithmetic: GF(2^255−19) elements as 20 limbs of 13 bits in int32.
Products of carried limbs are ≤2^26 and a 20-term accumulation stays under
2^31, so schoolbook multiplication is exact in int32 — no int64, which
TPUs lack natively.  2^260 ≡ 608 (mod p) folds the high limbs back in.

Verification is bit-exact against the host oracle: tests/test_ed25519.py
gates kernel accept/reject against crypto.ed25519_host.verify on valid,
corrupted, and structurally-invalid signatures.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import ed25519_host as host
from ..obsv import device as _device

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1
# 2^260 = 2^(20*13) ≡ 19 * 2^5 = 608 (mod p)
FOLD = 608


def int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(NLIMB, dtype=np.int32)
    for i in range(NLIMB):
        out[i] = x & MASK
        x >>= RADIX
    assert x == 0, "value out of range"
    return out


def limbs_to_int(limbs) -> int:
    total = 0
    for i, v in enumerate(np.asarray(limbs).tolist()):
        total += int(v) << (RADIX * i)
    return total


# Curve constants in limb form (host bigints -> arrays, baked at import).
_D2 = int_to_limbs((2 * host.D) % host.P)  # 2d, the unified-add constant
_BX = int_to_limbs(host.BASE[0])
_BY = int_to_limbs(host.BASE[1])
_BT = int_to_limbs(host.BASE[0] * host.BASE[1] % host.P)
_ZERO = np.zeros(NLIMB, dtype=np.int32)
_ONE = int_to_limbs(1)
_NINETEEN = int_to_limbs(19)


def _carry(x, nlimb: int = NLIMB):
    """Normalize limbs to [0, 2^13) with the 2^260 overflow folded back via
    608.  Three passes settle every case our magnitudes can produce
    (including the negative carries of subtraction)."""
    for _ in range(3):
        limbs = []
        carry = jnp.zeros_like(x[:, 0])
        for i in range(nlimb):
            v = x[:, i] + carry
            limbs.append(v & MASK)
            carry = v >> RADIX
        if nlimb > NLIMB:
            # Post-multiplication: the top carry is one more limb (weight
            # 2^(13*39)); limbs 20..39 fold back via 2^(13k) ≡ 608*2^(13(k-20)).
            limbs.append(carry)
            x = jnp.stack(limbs, axis=1)
            lo = x[:, :NLIMB]
            hi = x[:, NLIMB:]
            folded = jnp.zeros_like(lo)
            folded = folded.at[:, : hi.shape[1]].set(hi * FOLD)
            x = lo + folded
            nlimb = NLIMB
        else:
            limbs[0] = limbs[0] + carry * FOLD
            x = jnp.stack(limbs, axis=1)
    return x


# Constant (400, 39) 0/1 matrix routing outer-product entry (i, j) to
# convolution column i+j.  Expressing the schoolbook reduction as one
# integer dot keeps the traced graph ~100x smaller than 400 explicit
# multiply-adds (the ladder's scan body compiles in seconds instead of
# minutes) and the contraction is exact in int32.
_CONV = np.zeros((NLIMB * NLIMB, 2 * NLIMB - 1), dtype=np.int32)
for _i in range(NLIMB):
    for _j in range(NLIMB):
        _CONV[_i * NLIMB + _j, _i + _j] = 1


def _mul(a, b):
    """Schoolbook multiply-and-reduce: (batch, 20) x (batch, 20) -> carried
    (batch, 20).  Exact in int32 (see module docstring bounds)."""
    outer = (a[:, :, None] * b[:, None, :]).reshape(a.shape[0], NLIMB * NLIMB)
    c = jax.lax.dot_general(
        outer,
        jnp.asarray(_CONV),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _carry(c, nlimb=2 * NLIMB - 1)


def _add(a, b):
    return _carry(a + b)


def _sub(a, b):
    return _carry(a - b)


def _point_add(p, q):
    """Unified extended twisted-Edwards addition (add-2008-hwcd-3; complete
    for a=−1, so identity and doubling need no special cases — exactly what
    branch-free batched code wants)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = _mul(_sub(y1, x1), _sub(y2, x2))
    b = _mul(_add(y1, x1), _add(y2, x2))
    d2 = jnp.broadcast_to(jnp.asarray(_D2), x1.shape)
    c = _mul(_mul(t1, t2), d2)
    d = _mul(z1, z2)
    d = _add(d, d)
    e = _sub(b, a)
    f = _sub(d, c)
    g = _add(d, c)
    h = _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _canonical(x):
    """Carried limbs -> the unique representative in [0, p)."""
    hi = x[:, NLIMB - 1] >> 8  # bits 255.. of the value
    x = x.at[:, NLIMB - 1].set(x[:, NLIMB - 1] & 255)
    x = _carry(x.at[:, 0].add(hi * 19))
    for _ in range(2):
        # value >= p  <=>  value + 19 has bit 255 set
        t = _carry(x.at[:, 0].add(19))
        ge = (t[:, NLIMB - 1] >> 8) > 0
        t = t.at[:, NLIMB - 1].set(t[:, NLIMB - 1] & 255)
        x = jnp.where(ge[:, None], t, x)
    return x


def _feq(a, b):
    return jnp.all(_canonical(a) == _canonical(b), axis=1)


def ladder_impl(s_bits, k_bits, neg_a, r_affine):
    """[S]B + [k](−A), compared projectively against R.

    s_bits, k_bits: (batch, 256) int32 in MSB-first order.
    neg_a: tuple of 4 (batch, 20) limb tensors (extended coords of −A).
    r_affine: (rx, ry) limb tensors (Z=1 from host decompression).
    Returns (batch,) bool.

    Un-jitted implementation: parallel.sharding wraps it in shard_map to
    run the batch data-parallel across a device mesh; verify_batch uses
    the single-device jit below.
    """
    batch = s_bits.shape[0]

    def bc(const):
        return jnp.broadcast_to(jnp.asarray(const), (batch, NLIMB))

    identity = (bc(_ZERO), bc(_ONE), bc(_ONE), bc(_ZERO))
    base = (bc(_BX), bc(_BY), bc(_ONE), bc(_BT))

    def select(bit, point, other=identity):
        mask = bit[:, None]
        return tuple(
            jnp.where(mask != 0, pc, oc) for pc, oc in zip(point, other)
        )

    def step(acc, bits):
        sbit, kbit = bits
        acc = _point_add(acc, acc)
        acc = _point_add(acc, select(sbit, base))
        acc = _point_add(acc, select(kbit, neg_a))
        return acc, None

    acc, _ = jax.lax.scan(
        step,
        identity,
        (jnp.moveaxis(s_bits, 1, 0), jnp.moveaxis(k_bits, 1, 0)),
    )

    x, y, z, _t = acc
    rx, ry = r_affine
    ok_x = _feq(x, _mul(rx, z))
    ok_y = _feq(y, _mul(ry, z))
    # Reject the degenerate Z=0 encoding (cannot arise from valid inputs,
    # but the comparison 0 == 0 must not count as success).
    nonzero = jnp.logical_not(_feq(z, bc(_ZERO)))
    return ok_x & ok_y & nonzero


_ladder = jax.jit(ladder_impl)


def _bits_msb(x: int) -> np.ndarray:
    return np.array(
        [(x >> (255 - i)) & 1 for i in range(256)], dtype=np.int32
    )


def marshal_signature(pk: bytes, message: bytes, signature: bytes):
    """Host-side preparation of one signature for the device ladder:
    structural validation, point decompression, and the SHA-512 challenge.
    Returns (s_bits, k_bits, negA extended coords, R affine coords) or
    None if the signature is structurally invalid (rejected on the host)."""
    if len(pk) != 32 or len(signature) != 64:
        return None
    a = host.decompress(pk)
    r = host.decompress(signature[:32])
    if a is None or r is None:
        return None
    s = int.from_bytes(signature[32:], "little")
    if s >= host.L:
        return None
    k = (
        int.from_bytes(
            hashlib.sha512(signature[:32] + pk + message).digest(), "little"
        )
        % host.L
    )
    neg_a = host.point_negate(a)
    return (_bits_msb(s), _bits_msb(k), neg_a, (r[0], r[1]))


def pack_rows(rows: list, batch_floor: int = 8):
    """Stack marshalled rows into the ladder's input arrays, padding the
    batch axis to a power-of-two bucket (so only a few launch shapes ever
    compile) that is also a multiple of ``batch_floor`` — callers sharding
    over an n-device mesh pass batch_floor=n.  Padding rows replicate row
    0; their results must be discarded by the caller."""
    from .batching import next_pow2

    if not rows:
        raise ValueError(
            "pack_rows needs at least one row; an all-structurally-invalid "
            "batch has nothing to launch — skip the kernel call"
        )
    padded = next_pow2(len(rows), floor=batch_floor)
    padded += (-padded) % batch_floor
    rows_padded = rows + [rows[0]] * (padded - len(rows))
    s_bits = np.stack([row[0] for row in rows_padded])
    k_bits = np.stack([row[1] for row in rows_padded])
    neg_a = tuple(
        np.stack([int_to_limbs(row[2][c]) for row in rows_padded])
        for c in range(4)
    )
    r_aff = tuple(
        np.stack([int_to_limbs(row[3][c]) for row in rows_padded])
        for c in range(2)
    )
    return s_bits, k_bits, neg_a, r_aff


@_device.instrument("ed25519_verify")
def verify_batch(
    pks: list, messages: list, signatures: list, chunk: int = 512
) -> np.ndarray:
    """Verify a batch of Ed25519 signatures; returns (n,) bool.

    Structural failures (bad lengths, non-canonical S, undecodable points)
    are rejected on the host.  The rest launches in fixed-shape chunks
    *as marshalling proceeds*: JAX async dispatch runs chunk N's ladder on
    the device while the host decompresses/hashes chunk N+1, and results
    are only forced at the end — host prep and device compute overlap
    instead of serializing (each is roughly half the wall time).
    """
    n = len(pks)
    assert len(messages) == n and len(signatures) == n
    ok = np.zeros(n, dtype=bool)
    pending = []  # (indices, in-flight device words)
    rows: list = []
    indices: list = []

    def launch():
        nonlocal rows, indices
        if rows:
            pending.append((indices, _ladder(*pack_rows(rows))))
            rows, indices = [], []

    for i, (pk, msg, sig) in enumerate(zip(pks, messages, signatures)):
        row = marshal_signature(pk, msg, sig)
        if row is None:
            continue
        rows.append(row)
        indices.append(i)
        if len(rows) == chunk:
            launch()
    launch()

    for idx, words in pending:
        valid = np.asarray(words)
        for i, v in zip(idx, valid[: len(idx)]):
            ok[i] = bool(v)
    return ok
