"""Batched BLS12-381 G1 aggregation as a JAX kernel.

BASELINE ladder rung 4: quorum-certificate aggregation — summing the 2f+1
G1 signature points of each certificate — runs on the accelerator, one
lax.scan of complete point additions over the voter axis, vmapped across
a batch of certificates.  Pairing verification stays on the host
(crypto/bls_host.py): it is O(1) per certificate and pointer-heavy.

Field arithmetic: GF(p) for the 381-bit BLS prime as 30 limbs of 13 bits
in int32.  p has no sparse structure (unlike 2^255-19), so reduction is
**Montgomery REDC** with R = 2^390: each multiply is three 30x30 limb
convolutions (a*b, T_lo*N', m*p), exact in int32 — a convolution sums at
most 30 products of 26-bit values, staying under 2^31.  All elements are
kept in [0, 2p): REDC output lands there, and add/sub conditionally
subtract 2p.  Convolutions are expressed as one integer dot against a
constant routing matrix (same trick as ops/ed25519.py).

Point arithmetic: complete projective addition for y^2 = x^3 + b
(Renes–Costello–Batina 2016, Algorithm 7, a = 0) — branch-free, handles
identity and doubling, exactly what a masked scan wants.  b3 = 3*4 = 12
is applied with add chains, which commute with the Montgomery form.

Bit-exactness gate: tests/test_bls.py aggregates random signature sets on
the device and compares against crypto.bls_host.aggregate_g1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bls_host as host
from ..obsv import device as _device

NLIMB = 30
RADIX = 13
MASK = (1 << RADIX) - 1
RBITS = NLIMB * RADIX  # 390
R = 1 << RBITS
P_INT = host.P
P2_INT = 2 * host.P
# N' = -p^(-1) mod R, the Montgomery constant.
NPRIME_INT = (-pow(P_INT, -1, R)) % R


def int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(NLIMB, dtype=np.int32)
    for i in range(NLIMB):
        out[i] = x & MASK
        x >>= RADIX
    assert x == 0, "value out of range"
    return out


def limbs_to_int(limbs) -> int:
    total = 0
    for i, v in enumerate(np.asarray(limbs).tolist()):
        total += int(v) << (RADIX * i)
    return total


_P_LIMBS = int_to_limbs(P_INT)
_P2_LIMBS = int_to_limbs(P2_INT)
_NPRIME_LIMBS = int_to_limbs(NPRIME_INT)

# (900, 59) routing matrix: outer-product entry (i, j) -> column i + j.
_CONV = np.zeros((NLIMB * NLIMB, 2 * NLIMB - 1), dtype=np.int32)
for _i in range(NLIMB):
    for _j in range(NLIMB):
        _CONV[_i * NLIMB + _j, _i + _j] = 1


def _conv(a, b):
    """(batch, 30) x (batch, 30) -> (batch, 59) limb convolution (exact)."""
    outer = (a[:, :, None] * b[:, None, :]).reshape(a.shape[0], NLIMB * NLIMB)
    return jax.lax.dot_general(
        outer,
        jnp.asarray(_CONV),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def _carry(x, nlimb):
    """One signed carry pass: limbs -> [0, 2^13), returns (limbs, carry_out).
    Arithmetic shifts make this exact for negative values too."""
    limbs = []
    carry = jnp.zeros_like(x[:, 0])
    for i in range(nlimb):
        v = x[:, i] + carry
        limbs.append(v & MASK)
        carry = v >> RADIX
    return jnp.stack(limbs, axis=1), carry


def _mont_mul(a, b):
    """Montgomery product abR^{-1} mod p; inputs/outputs in [0, 2p) with
    carried (13-bit) limbs."""
    t = _conv(a, b)  # 59 limbs, values < 30*2^26
    t, t_top = _carry(t, 2 * NLIMB - 1)  # exact limbs + carry (limb 59)
    t_lo = t[:, :NLIMB]
    m = _conv(t_lo, jnp.broadcast_to(jnp.asarray(_NPRIME_LIMBS), t_lo.shape))
    m, _ = _carry(m, 2 * NLIMB - 1)
    m_lo = m[:, :NLIMB]  # m = T_lo * N' mod R
    mp = _conv(m_lo, jnp.broadcast_to(jnp.asarray(_P_LIMBS), m_lo.shape))
    # T + m*p: 60-limb sum; the low 30 limbs are divisible by R by
    # construction, so after a carry pass they are exactly zero.
    total = jnp.zeros((a.shape[0], 2 * NLIMB + 1), dtype=jnp.int32)
    total = total.at[:, : 2 * NLIMB - 1].set(t)
    total = total.at[:, 2 * NLIMB - 1].set(t_top)
    total = total.at[:, : 2 * NLIMB - 1].add(mp)
    total, top = _carry(total, 2 * NLIMB + 1)
    out = total[:, NLIMB : 2 * NLIMB]  # the /R shift
    out = out.at[:, NLIMB - 1].add(
        (total[:, 2 * NLIMB] + (top << RADIX)) << RADIX
    )
    # t = (T + mp)/R < 2p < 2^382 fits 30 limbs; the add above folds the
    # top two (always tiny) limbs back in, then one carry settles.
    out, _ = _carry(out, NLIMB)
    return out


def _cond_sub_2p(x):
    """x in [0, 4p) -> x mod 2p, branch-free."""
    t = x - jnp.asarray(_P2_LIMBS)
    t, borrow = _carry(t, NLIMB)
    # borrow == -1 iff x < 2p.
    keep = (borrow < 0)[:, None]
    return jnp.where(keep, x, t)


def _add(a, b):
    s, _ = _carry(a + b, NLIMB)  # < 4p, no carry-out (fits 30 limbs)
    return _cond_sub_2p(s)


def _sub(a, b):
    s, _ = _carry(a - b + jnp.asarray(_P2_LIMBS), NLIMB)  # in (0, 4p)
    return _cond_sub_2p(s)


def _mul_small(x, n: int):
    """n*x for tiny n via add chains (stays in [0, 2p))."""
    out = None
    acc = x
    while n:
        if n & 1:
            out = acc if out is None else _add(out, acc)
        n >>= 1
        if n:
            acc = _add(acc, acc)
    return out


def _point_add(p1, p2):
    """Complete projective addition on y^2 = x^3 + 4 (RCB16 Alg. 7, a=0,
    b3=12); coordinates are Montgomery-form limb tensors."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    t0 = _mont_mul(x1, x2)
    t1 = _mont_mul(y1, y2)
    t2 = _mont_mul(z1, z2)
    t3 = _mont_mul(_add(x1, y1), _add(x2, y2))
    t3 = _sub(t3, _add(t0, t1))
    t4 = _mont_mul(_add(y1, z1), _add(y2, z2))
    t4 = _sub(t4, _add(t1, t2))
    x3 = _mont_mul(_add(x1, z1), _add(x2, z2))
    x3 = _sub(x3, _add(t0, t2))  # X1Z2 + X2Z1
    t0 = _mul_small(t0, 3)
    t2 = _mul_small(t2, 12)  # b3 * Z1Z2
    z3 = _add(t1, t2)
    t1 = _sub(t1, t2)
    y3 = _mul_small(x3, 12)  # b3 * (X1Z2 + X2Z1)
    x3 = _sub(_mont_mul(t3, t1), _mont_mul(t4, y3))
    y3 = _add(_mont_mul(y3, t0), _mont_mul(t1, z3))
    z3 = _add(_mont_mul(z3, t4), _mont_mul(t0, t3))
    return (x3, y3, z3)


@jax.jit
def _aggregate(xs, ys, zs, mask):
    """Masked sum over the voter axis.

    xs/ys/zs: (batch, voters, 30) Montgomery-form projective coordinates;
    mask: (batch, voters) int32 (0 drops the voter).
    Returns (batch, 3, 30)."""
    batch = xs.shape[0]
    mont_one = jnp.broadcast_to(
        jnp.asarray(int_to_limbs(R % P_INT)), (batch, NLIMB)
    )
    zero = jnp.zeros((batch, NLIMB), dtype=jnp.int32)
    identity = (zero, mont_one, zero)

    def step(acc, inputs):
        x, y, z, live = inputs
        keep = (live != 0)[:, None]
        point = (
            jnp.where(keep, x, identity[0]),
            jnp.where(keep, y, identity[1]),
            jnp.where(keep, z, identity[2]),
        )
        return _point_add(acc, point), None

    acc, _ = jax.lax.scan(
        step,
        identity,
        (
            jnp.moveaxis(xs, 1, 0),
            jnp.moveaxis(ys, 1, 0),
            jnp.moveaxis(zs, 1, 0),
            jnp.moveaxis(mask, 1, 0),
        ),
    )
    return jnp.stack(acc, axis=1)


def _to_mont(x: int) -> np.ndarray:
    return int_to_limbs(x * R % P_INT)


def _from_mont(limbs) -> int:
    return limbs_to_int(limbs) * pow(R, -1, P_INT) % P_INT


@_device.instrument("bls_aggregate")
def aggregate_signatures(cert_sigs: list, voters: int | None = None):
    """Aggregate a batch of quorum certificates on the device.

    cert_sigs: list of certificates, each a list of affine G1 points
    (or None for absent voters).  Returns a list of affine aggregate
    points (or None), bit-equal to host aggregation.
    """
    if not cert_sigs:
        return []
    from .batching import next_pow2

    # Power-of-two padding on both axes (absent-voter masking makes the
    # padding rows free) so only a few launch shapes ever compile.
    width = next_pow2(voters or max(len(c) for c in cert_sigs), floor=4)
    batch = next_pow2(len(cert_sigs), floor=4)
    xs = np.zeros((batch, width, NLIMB), dtype=np.int32)
    ys = np.zeros((batch, width, NLIMB), dtype=np.int32)
    zs = np.zeros((batch, width, NLIMB), dtype=np.int32)
    mask = np.zeros((batch, width), dtype=np.int32)
    for b, cert in enumerate(cert_sigs):
        for v, point in enumerate(cert):
            if point is None:
                continue
            xs[b, v] = _to_mont(point[0])
            ys[b, v] = _to_mont(point[1])
            zs[b, v] = _to_mont(1)
            mask[b, v] = 1
    out = np.asarray(_aggregate(xs, ys, zs, mask))
    results = []
    for b in range(len(cert_sigs)):
        x = _from_mont(out[b, 0])
        y = _from_mont(out[b, 1])
        z = _from_mont(out[b, 2])
        if z == 0:
            results.append(None)
            continue
        zi = pow(z, P_INT - 2, P_INT)
        results.append((x * zi % P_INT, y * zi % P_INT))
    return results
