"""Batched SHA-256 as a JAX kernel.

Bit-exact with hashlib.sha256 (FIPS 180-4) over the preimage layouts in
core.preimage — that equality is the correctness gate (tests/test_sha256.py)
and what makes a TPU run and a CPU-hash run produce identical event logs.

Design for TPU:
- Messages are padded on the host (standard SHA-256 padding) and packed into
  a (batch, max_blocks, 16) uint32 tensor of big-endian words plus a (batch,)
  block-count vector (ops.batching).  All shapes static per bucket.
- The compression function is written over the whole batch at once: every
  round's adds/rotates/xors are (batch,)-shaped vector ops, so XLA maps them
  onto the VPU's 8x128 lanes across the batch dimension.  The 64 rounds are
  unrolled (static Python loop) — a single fused kernel per block index.
- Variable block counts are handled with a masked lax.scan over the block
  axis: all messages advance through max_blocks compressions, but a
  message's state freezes once its own block count is exhausted.  This keeps
  control flow static (no data-dependent branching under jit).
- Bucketed padding: callers round max_blocks and batch up to buckets
  (ops.batching) so only a handful of shapes ever compile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..obsv import device as _device

# fmt: off
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_IV = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)
# fmt: on


def _rotr(x, n):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


# Partial unroll factor for the round/schedule scans: keeps the emitted HLO
# small (fast compiles on every backend — fully unrolling the 64 rounds
# takes *minutes* under CPU XLA) while giving the backend straight-line
# stretches to software-pipeline.
_UNROLL = 8


def _compress_batch(state, block):
    """One SHA-256 compression over a whole batch.

    state: (batch, 8) uint32; block: (batch, 16) uint32 → (batch, 8).

    Both the message-schedule expansion and the 64 rounds are lax.scans
    whose bodies are fully (batch,)-vectorized — the batch dimension rides
    the VPU lanes; the sequential dependency lives in the scan."""
    # Message schedule: carry a rolling 16-word window, emit w_t.
    window0 = jnp.moveaxis(block, 1, 0)  # (16, batch)

    def sched_body(window, _):
        w15, w2 = window[1], window[14]
        w16, w7 = window[0], window[9]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
        wt = w16 + s0 + w7 + s1
        return jnp.concatenate([window[1:], wt[None]], axis=0), wt

    _, w_rest = jax.lax.scan(
        sched_body, window0, None, length=48, unroll=_UNROLL
    )
    w_all = jnp.concatenate([window0, w_rest], axis=0)  # (64, batch)

    def round_body(vars8, inputs):
        wt, kt = inputs
        a, b, c, d, e, f, g, h = vars8
        big_s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + big_s1 + ch + kt + wt
        big_s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + big_s0 + maj, a, b, c, d + t1, e, f, g), None

    vars8, _ = jax.lax.scan(
        round_body,
        tuple(state[:, i] for i in range(8)),
        (w_all, jnp.asarray(_K)),
        unroll=_UNROLL,
    )
    return state + jnp.stack(vars8, axis=1)


@functools.partial(jax.jit, static_argnames=("max_blocks",))
def _sha256_blocks(blocks, n_blocks, *, max_blocks: int):
    """blocks: (batch, max_blocks, 16) uint32 big-endian words;
    n_blocks: (batch,) int32 — actual block count per message.
    Returns (batch, 8) uint32 digest words."""
    batch = blocks.shape[0]
    init = jnp.broadcast_to(jnp.asarray(_IV), (batch, 8))

    def body(state, inputs):
        block, j = inputs
        new_state = _compress_batch(state, block)
        live = (j < n_blocks)[:, None]
        return jnp.where(live, new_state, state), None

    state, _ = jax.lax.scan(
        body,
        init,
        (jnp.moveaxis(blocks, 1, 0), jnp.arange(max_blocks, dtype=jnp.int32)),
    )
    return state


@_device.instrument("sha256_digest")
def sha256_digest_words(blocks, n_blocks):
    """Run the kernel on pre-packed blocks (see ops.batching)."""
    return _sha256_blocks(blocks, n_blocks, max_blocks=blocks.shape[1])


@functools.partial(jax.jit, static_argnames=("iters",))
def sha256_chain_checksum(block, *, iters: int):
    """Benchmark kernel: ``iters`` chained compressions over one (batch, 16)
    block tensor, reduced to a scalar checksum.

    Measuring device throughput through an RPC-tunneled backend is subtle:
    ``block_until_ready`` may not actually wait, and repeated identical
    launches can be served from a cache — so an honest timing needs (a) all
    the work inside ONE launch with a sequential dependency chain, (b) a
    scalar readback as the only sync, and (c) distinct inputs per call.
    This helper provides (a)+(b); the caller supplies (c).
    """
    batch = block.shape[0]
    state0 = jnp.broadcast_to(jnp.asarray(_IV), (batch, 8))

    def body(state, _):
        return _compress_batch(state, block), None

    state, _ = jax.lax.scan(body, state0, None, length=iters)
    return jnp.sum(state, dtype=jnp.uint32)


# sync=False: the checksum's measurement protocol (one launch, scalar
# readback as the only sync) must not gain a block_until_ready.
sha256_chain_checksum = _device.instrument("sha256_chain", sync=False)(
    sha256_chain_checksum
)


def sha256_chunked(chunk_lists: list) -> list:
    """Digest a batch of chunked preimages (the Actions.hashes shape: each
    item is a list of byte chunks, digested over their concatenation).  The
    executor-facing entry point for offloading a whole action batch."""
    return sha256_many([b"".join(chunks) for chunks in chunk_lists])


def sha256(message: bytes) -> bytes:
    """Single-message convenience wrapper (prefer sha256_many for batches)."""
    return sha256_many([message])[0]


def sha256_many(messages: list) -> list:
    """Digest a list of byte strings on the accelerator, preserving order.

    Messages are grouped by power-of-two padded block count, one kernel
    launch per group: only a few shapes ever compile, and a single long
    message doesn't force every short row through its block count."""
    from .batching import next_pow2, pack_preimages, sha256_pad

    if not messages:
        return []

    groups: dict[int, list] = {}  # block bucket -> original indices
    for i, msg in enumerate(messages):
        bucket = next_pow2((len(sha256_pad(msg)) // 64))
        groups.setdefault(bucket, []).append(i)

    out: list = [None] * len(messages)
    for bucket in sorted(groups):
        indices = groups[bucket]
        batch = pack_preimages([messages[i] for i in indices])
        words = sha256_digest_words(batch.blocks, batch.n_blocks)
        raw = np.asarray(words).astype(">u4").tobytes()
        for row, i in enumerate(indices):
            out[i] = raw[32 * row : 32 * row + 32]
    return out
