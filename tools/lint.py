"""Static-analysis gate — thin CLI shim over ``tools/analysis/``.

The reference CI runs staticcheck + the race detector on every build
(reference: .travis.yml:16-18).  The checks themselves live in the
``tools/analysis`` package:

- ``analysis/rules_w.py`` — general defect classes W1..W12
- ``analysis/rules_d.py`` — determinism purity auditor D101..D104
  (transitive proof that core/ and the deterministic testengine never
  reach an impure effect)
- ``analysis/rules_c.py`` — concurrency checker C201..C203 (the
  ``# guarded-by:`` / ``# holds:`` convention)
- ``analysis/engine.py``  — registry, per-line suppressions
  (``# lint: allow W7 <reason>`` — reason mandatory), committed
  baseline, ``--json`` output

Run: ``python tools/lint.py [--json] [paths...]`` — exits non-zero on
non-baselined findings.  Policy and the rule catalog: docs/ANALYSIS.md.

This module keeps the original helper API (``check_file``, ``lint``,
``_in_monotonic_scope``, the scope constants) so existing invocations
and tests keep working unchanged.
"""

from __future__ import annotations

import sys
from pathlib import Path

# Allow both `python tools/lint.py` (tools/ becomes sys.path[0]) and
# `import lint` from a test that put tools/ on sys.path.
_TOOLS_DIR = str(Path(__file__).resolve().parent)
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import cli as _cli  # noqa: E402
from analysis import engine as _engine  # noqa: E402
from analysis import rules_w as _rules_w  # noqa: E402
from analysis.engine import FileContext, all_rules  # noqa: E402

# Re-exported scope constants (part of the historical API).
MONOTONIC_ONLY_TREES = _rules_w.MONOTONIC_ONLY_TREES
SOCKET_ALLOWED_FILES = _rules_w.SOCKET_ALLOWED_FILES
FSYNC_ALLOWED_FILES = _rules_w.FSYNC_ALLOWED_FILES
THREAD_BAN_FILE = _rules_w.THREAD_BAN_FILE
THREAD_SPAWN_HELPER = _rules_w.THREAD_SPAWN_HELPER
PROCESS_ALLOWED_TREE = _rules_w.PROCESS_ALLOWED_TREE
PROCESS_MODULES = _rules_w.PROCESS_MODULES


def _in_monotonic_scope(path: Path) -> bool:
    return _rules_w.in_monotonic_scope(path.resolve().as_posix())


def check_file(path: Path, monotonic_only: bool | None = None) -> list[str]:
    """Lint one file with the per-file rules.  ``monotonic_only`` forces
    the W7 wall-clock check on (True) or off (False); None scopes it by
    MONOTONIC_ONLY_TREES.  Project-wide rules (the D1xx auditor) need
    the whole tree — use :func:`lint` or the CLI for those."""
    ctx = FileContext(path)
    if ctx.syntax_error is not None:
        return [
            f"{path}:{ctx.syntax_error.lineno}: E0 syntax error: "
            f"{ctx.syntax_error.msg}"
        ]
    findings = []
    for rule in all_rules():
        if rule.check is None or rule.project:
            continue
        if rule.id == "W7":
            forced = (
                monotonic_only
                if monotonic_only is not None
                else _rules_w.in_monotonic_scope(ctx.posix)
            )
            if forced:
                findings.extend(_rules_w.check_w7(ctx))
            continue
        if rule.scope is not None and not rule.scope(ctx.posix):
            continue
        findings.extend(rule.check(ctx))
    findings = _engine._apply_suppressions([ctx], findings)
    findings.sort(key=lambda f: (f.line, f.rule))
    return [f.render() for f in findings]


def lint(paths: list[Path]) -> list[str]:
    """Run the full suite (W+D+C) over ``paths`` with the committed
    baseline applied; returns rendered finding lines."""
    baseline = _engine.load_baseline(_cli.BASELINE_PATH)
    result = _engine.run(paths, repo_root=_cli.REPO, baseline=baseline)
    return result.render()


def main(argv: list[str]) -> int:
    return _cli.main(argv)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
