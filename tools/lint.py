"""Stdlib-only static-analysis gate.

The reference CI runs staticcheck + the race detector on every build
(reference: .travis.yml:16-18).  This environment ships no third-party
linter, so the equivalent discipline is a small AST-based checker that
enforces the defect classes that have actually bitten BFT codebases:

- W1 unused import            (dead seams hide refactor mistakes)
- W2 bare ``except:``         (swallows KeyboardInterrupt/SystemExit)
- W3 assert on a tuple literal (always true — a silently-disabled check)
- W4 ``is``/``is not`` against str/int literals (identity vs equality)
- W5 mutable default argument  (shared-state bug factory)
- W6 f-string with no placeholders (usually a forgotten interpolation)
- W7 wall-clock ``time.time()`` in monotonic-only code (instrumented /
  latency-measuring paths must use ``time.perf_counter`` — the wall
  clock steps under NTP and breaks span nesting and histograms).  W7 is
  *scoped*: it applies only to files under the trees named in
  ``MONOTONIC_ONLY_TREES`` (or when forced via the ``monotonic_only``
  parameter); eventlog timestamps, for example, legitimately want the
  wall clock.
- W8 ``http.server`` outside ``mirbft_tpu/obsv/`` — metric/status
  exposition must go through the obsv exporter and its catalog
  renderer; ad-hoc handlers writing registry internals onto sockets
  bypass the catalog/cardinality contract.  Scoped to ``mirbft_tpu/``
  (tests and tools may use HTTP clients/servers freely).
- W9 raw ``socket`` outside ``mirbft_tpu/runtime/transport.py`` and
  ``mirbft_tpu/chaos/live.py`` — all wire I/O flows through the
  transport (framing, reconnect/backoff, counters, fault seam) or the
  live chaos driver's partition proxies; a stray socket elsewhere
  bypasses every one of those disciplines.  Scoped to ``mirbft_tpu/``
  (tests and tools may open sockets freely).
- W10 durability/pipeline discipline, two prongs.  (a) ``os.fsync``
  outside ``mirbft_tpu/runtime/storage.py`` and the live chaos
  driver's durable app log — the stores' group-commit coalescer is the
  only fsync authority; a stray fsync elsewhere silently reintroduces
  the per-batch sync cost the pipelined commit path exists to amortize.
  (b) raw ``threading.Thread`` creation in
  ``mirbft_tpu/runtime/processor.py`` outside the pipeline's
  ``_spawn_stage`` helper — stage threads must go through the single
  creation point so naming (``proc-pipe-*``), daemonization, and the
  leak gate stay uniform.  Scoped to ``mirbft_tpu/``.
- W11 ``subprocess``/``multiprocessing`` outside ``mirbft_tpu/cluster/``
  — process management (spawn, readiness handshake, kill/restart,
  teardown) is the cluster supervisor's whole job; a stray Popen or
  Process elsewhere forks workers that escape the supervisor's
  lifecycle, log capture, and teardown sweep.  Scoped to
  ``mirbft_tpu/`` (tests, tools, and bench may fork freely).

Run: ``python tools/lint.py [paths...]`` — exits non-zero on findings.
Also enforced in CI-equivalent form by ``tests/test_lint.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path


class _ImportTracker(ast.NodeVisitor):
    """Collect imported names and every name usage per module."""

    def __init__(self):
        self.imports: dict[str, tuple[int, str]] = {}  # name -> (line, what)
        self.used: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            # ``import x as x`` is the conventional re-export idiom: keep.
            if alias.asname is not None and alias.asname == alias.name:
                continue
            self.imports[name] = (node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # compiler directive, not a binding
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            if alias.asname is not None and alias.asname == alias.name:
                continue
            self.imports[name] = (node.lineno, alias.name)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)


def _string_uses(tree: ast.Module) -> set[str]:
    """Names referenced from ``__all__`` string entries (the re-export
    idiom).  Only those assignments count — treating any identifier-shaped
    string anywhere as a use would let a stray dict key mask a genuinely
    unused import."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AugAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        if not any(
            isinstance(t, ast.Name) and t.id == "__all__" for t in targets
        ):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                out.add(sub.value)
    return out


# Path fragments whose files must never read the wall clock: span/metric
# durations and simulated-time code.  testengine/eventlog.py (run metadata
# timestamps) and bench/test files are deliberately outside the scope.
MONOTONIC_ONLY_TREES = (
    "mirbft_tpu/obsv/",
    "mirbft_tpu/core/",
    "mirbft_tpu/runtime/",
    "mirbft_tpu/chaos/",
    "mirbft_tpu/testengine/crypto_plane.py",
    "mirbft_tpu/testengine/signing.py",
)


def _in_monotonic_scope(path: Path) -> bool:
    posix = path.resolve().as_posix()
    return any(fragment in posix for fragment in MONOTONIC_ONLY_TREES)


def _in_exposition_scope(path: Path) -> bool:
    """True for mirbft_tpu files outside obsv/ — where W8 bans
    http.server."""
    posix = path.resolve().as_posix()
    return "mirbft_tpu/" in posix and "mirbft_tpu/obsv/" not in posix


# The only two files allowed to touch raw sockets: the transport owns
# framing/reconnect/counters, and the live chaos driver's partition
# proxies sit deliberately *under* the transport at the socket layer.
SOCKET_ALLOWED_FILES = (
    "mirbft_tpu/runtime/transport.py",
    "mirbft_tpu/chaos/live.py",
)


def _in_socket_ban_scope(path: Path) -> bool:
    """True for mirbft_tpu files where W9 bans raw ``socket`` imports."""
    posix = path.resolve().as_posix()
    return "mirbft_tpu/" in posix and not any(
        posix.endswith(allowed) for allowed in SOCKET_ALLOWED_FILES
    )


# The only files allowed to call os.fsync: the stores own the
# group-commit coalescer, and the live chaos driver's durable app log
# models an application fsyncing its own state (deliberately outside the
# group-commit path, like a real app would be).
FSYNC_ALLOWED_FILES = (
    "mirbft_tpu/runtime/storage.py",
    "mirbft_tpu/chaos/live.py",
)

# The one module (and the one helper inside it) allowed to create
# pipeline threads.
THREAD_BAN_FILE = "mirbft_tpu/runtime/processor.py"
THREAD_SPAWN_HELPER = "_spawn_stage"


def _in_fsync_ban_scope(path: Path) -> bool:
    """True for mirbft_tpu files where W10 bans ``os.fsync``."""
    posix = path.resolve().as_posix()
    return "mirbft_tpu/" in posix and not any(
        posix.endswith(allowed) for allowed in FSYNC_ALLOWED_FILES
    )


# The only tree allowed to manage OS processes: the cluster supervisor
# owns spawn/handshake/kill/restart/teardown for process-per-node runs.
PROCESS_ALLOWED_TREE = "mirbft_tpu/cluster/"

# Modules whose import anywhere else in mirbft_tpu/ trips W11.
PROCESS_MODULES = ("subprocess", "multiprocessing")


def _in_process_ban_scope(path: Path) -> bool:
    """True for mirbft_tpu files where W11 bans process-management
    imports."""
    posix = path.resolve().as_posix()
    return "mirbft_tpu/" in posix and PROCESS_ALLOWED_TREE not in posix


def _spawn_helper_spans(tree: ast.Module) -> list[tuple[int, int]]:
    """Line spans of every ``_spawn_stage`` definition (the only place
    W10 permits ``threading.Thread(...)`` in the processor module)."""
    return [
        (node.lineno, node.end_lineno or node.lineno)
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name == THREAD_SPAWN_HELPER
    ]


def check_file(path: Path, monotonic_only: bool | None = None) -> list[str]:
    """Lint one file.  ``monotonic_only`` forces the W7 wall-clock check
    on (True) or off (False); None scopes it by MONOTONIC_ONLY_TREES."""
    if monotonic_only is None:
        monotonic_only = _in_monotonic_scope(path)
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as err:
        return [f"{path}:{err.lineno}: E0 syntax error: {err.msg}"]

    findings: list[str] = []

    tracker = _ImportTracker()
    tracker.visit(tree)
    stringy = _string_uses(tree)
    is_package_init = path.name == "__init__.py"
    for name, (line, what) in sorted(tracker.imports.items()):
        if name in tracker.used or name in stringy:
            continue
        if is_package_init:
            continue  # package __init__ imports are the public surface
        findings.append(f"{path}:{line}: W1 unused import '{what}'")

    in_thread_ban_file = path.resolve().as_posix().endswith(THREAD_BAN_FILE)
    spawn_spans = _spawn_helper_spans(tree) if in_thread_ban_file else []

    # Format specs (the ``:6d`` in an f-string) are themselves JoinedStr
    # nodes; they must not trip the W6 empty-f-string check.
    spec_ids = {
        id(n.format_spec)
        for n in ast.walk(tree)
        if isinstance(n, ast.FormattedValue) and n.format_spec is not None
    }

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(f"{path}:{node.lineno}: W2 bare 'except:'")
        if isinstance(node, ast.Assert) and isinstance(node.test, ast.Tuple):
            if node.test.elts:
                findings.append(
                    f"{path}:{node.lineno}: W3 assert on tuple is always true"
                )
        if isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Is, ast.IsNot)) and isinstance(
                    comp, ast.Constant
                ) and isinstance(comp.value, (str, int, bytes)) and not isinstance(
                    comp.value, bool
                ):
                    findings.append(
                        f"{path}:{node.lineno}: W4 'is' comparison with literal"
                    )
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    findings.append(
                        f"{path}:{default.lineno}: W5 mutable default argument"
                    )
        if isinstance(node, ast.JoinedStr) and id(node) not in spec_ids:
            if not any(
                isinstance(v, ast.FormattedValue) for v in node.values
            ):
                findings.append(
                    f"{path}:{node.lineno}: W6 f-string without placeholders"
                )
        if monotonic_only:
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "time"
                and isinstance(node.value, ast.Name)
                and node.value.id == "time"
            ):
                findings.append(
                    f"{path}:{node.lineno}: W7 wall-clock time.time() in "
                    "monotonic-only code (use time.perf_counter)"
                )
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                if any(alias.name == "time" for alias in node.names):
                    findings.append(
                        f"{path}:{node.lineno}: W7 'from time import time' in "
                        "monotonic-only code (use time.perf_counter)"
                    )
        if _in_exposition_scope(path):
            hit = False
            if isinstance(node, ast.Import):
                hit = any(
                    alias.name == "http.server" or alias.name.startswith("http.server.")
                    for alias in node.names
                )
            elif isinstance(node, ast.ImportFrom):
                hit = node.module is not None and (
                    node.module == "http.server"
                    or node.module.startswith("http.server.")
                    or (
                        node.module == "http"
                        and any(alias.name == "server" for alias in node.names)
                    )
                )
            if hit:
                findings.append(
                    f"{path}:{node.lineno}: W8 http.server outside obsv/ "
                    "(exposition must go through obsv.exporter and the "
                    "catalog renderer)"
                )
        if _in_socket_ban_scope(path):
            hit = False
            if isinstance(node, ast.Import):
                hit = any(
                    alias.name == "socket" or alias.name.startswith("socket.")
                    for alias in node.names
                )
            elif isinstance(node, ast.ImportFrom):
                hit = node.module is not None and (
                    node.module == "socket"
                    or node.module.startswith("socket.")
                )
            if hit:
                findings.append(
                    f"{path}:{node.lineno}: W9 raw socket outside "
                    "runtime/transport.py and chaos/live.py (wire I/O "
                    "goes through the transport or the live driver's "
                    "partition proxies)"
                )
        if _in_fsync_ban_scope(path):
            hit = (
                isinstance(node, ast.Attribute)
                and node.attr == "fsync"
                and isinstance(node.value, ast.Name)
                and node.value.id == "os"
            ) or (
                isinstance(node, ast.ImportFrom)
                and node.module == "os"
                and any(alias.name == "fsync" for alias in node.names)
            )
            if hit:
                findings.append(
                    f"{path}:{node.lineno}: W10 os.fsync outside "
                    "runtime/storage.py (durability goes through the "
                    "stores' sync()/sync_token() group-commit API)"
                )
        if _in_process_ban_scope(path):
            hit = False
            if isinstance(node, ast.Import):
                hit = any(
                    alias.name in PROCESS_MODULES
                    or alias.name.startswith(tuple(m + "." for m in PROCESS_MODULES))
                    for alias in node.names
                )
            elif isinstance(node, ast.ImportFrom):
                hit = node.module is not None and (
                    node.module in PROCESS_MODULES
                    or node.module.startswith(
                        tuple(m + "." for m in PROCESS_MODULES)
                    )
                )
            if hit:
                findings.append(
                    f"{path}:{node.lineno}: W11 subprocess/multiprocessing "
                    "outside cluster/ (process lifecycle goes through the "
                    "cluster supervisor)"
                )
        if in_thread_ban_file and isinstance(node, ast.Call):
            func = node.func
            hit = (
                isinstance(func, ast.Attribute)
                and func.attr == "Thread"
                and isinstance(func.value, ast.Name)
                and func.value.id == "threading"
            ) or (isinstance(func, ast.Name) and func.id == "Thread")
            if hit and not any(
                lo <= node.lineno <= hi for lo, hi in spawn_spans
            ):
                findings.append(
                    f"{path}:{node.lineno}: W10 raw threading.Thread in "
                    "runtime/processor.py outside _spawn_stage (stage "
                    "threads go through the single creation point)"
                )

    return findings


def lint(paths: list[Path]) -> list[str]:
    findings: list[str] = []
    for root in paths:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            findings.extend(check_file(f))
    return findings


def main(argv: list[str]) -> int:
    repo = Path(__file__).resolve().parent.parent
    targets = (
        [Path(a) for a in argv]
        if argv
        else [repo / "mirbft_tpu", repo / "tests", repo / "tools",
              repo / "bench.py", repo / "__graft_entry__.py"]
    )
    findings = lint(targets)
    for line in findings:
        print(line)
    print(f"lint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
