"""General defect classes W1..W20 (the original tools/lint.py checks as
Rule objects, message-compatible, plus the seeded-randomness ban and the
adversary-tooling, resource-introspection, device-timing, and
snapshot-I/O confinements).

The catalog (rationale per rule lives in docs/ANALYSIS.md):

- W1 unused import            (dead seams hide refactor mistakes)
- W2 bare ``except:``         (swallows KeyboardInterrupt/SystemExit)
- W3 assert on a tuple literal (always true — a silently-disabled check)
- W4 ``is``/``is not`` against str/int literals (identity vs equality)
- W5 mutable default argument  (shared-state bug factory)
- W6 f-string with no placeholders (usually a forgotten interpolation)
- W7 wall-clock ``time.time()`` in monotonic-only code
- W8 ``http.server`` outside ``mirbft_tpu/obsv/``
- W9 raw ``socket`` outside transport.py / chaos/live.py
- W10 ``os.fsync`` outside storage.py; raw Thread in processor.py
- W11 ``subprocess``/``multiprocessing`` outside ``mirbft_tpu/cluster/``
- W12 unseeded ``random.*`` module-level functions and ``numpy.random``
  legacy global state inside ``mirbft_tpu/`` — seeded
  ``random.Random(seed)`` instances and ``jax.random`` keys only.
  Seeded reproducibility is the chaos/testengine contract: every fault
  schedule, mangler decision, arrival process, and jitter sequence must
  replay from its seed.
- W13 adversary tooling (``mirbft_tpu.testengine`` / ``mirbft_tpu.chaos``
  — payload mutation, frame rewriting, fault injection) imported from
  ``core/`` or ``runtime/``.  The protocol must not depend on its own
  attack harness; the flow is strictly one-way (the harness wraps the
  protocol, never the reverse).
- W14 ``resource``/``psutil`` outside ``mirbft_tpu/obsv/resources.py``
  — process introspection (RSS, fd counts, rusage) goes through the
  obsv resource sampler so the sampling cadence, gauge names, and leak
  fits stay in one place.
- W15 ``jax.profiler`` / ``block_until_ready`` outside
  ``mirbft_tpu/obsv/device.py`` and ``mirbft_tpu/ops/`` — device
  synchronization and profiler hooks are confined to the kernel layer
  and its instrumentation wrapper.  A stray ``block_until_ready`` in
  protocol code serializes the device pipeline (a silent perf cliff),
  and scattered profiler sessions fight over the single trace backend.
- W16 ``jax``/``jax.numpy`` imports inside ``mirbft_tpu/core/`` outside
  ``core/device_tracker.py`` — the protocol state machine is pure
  deterministic Python (the purity auditor's root set); the device ack
  plane is its single sanctioned accelerator boundary.  A stray jnp
  import anywhere else in core/ either drags device nondeterminism into
  replayed state or silently forces host transfers on the hot path.
- W17 snapshot file I/O (``write_snapshot_file`` / ``read_snapshot_file``
  / ``remove_snapshot_file``) outside ``runtime/storage.py`` and
  ``runtime/transfer.py`` — the staged-snapshot crash contract (tmp +
  fsync + rename, resume-on-restart, WAL-independent adoption
  authority) lives in exactly two files.  A third call site would fork
  the atomicity/cleanup discipline and let a crash mid-transfer leave
  state the restart path does not know how to interpret.
- W18 app-state file I/O (``write_app_state`` / ``read_app_state`` /
  ``remove_app_state``) outside ``runtime/storage.py`` and
  ``mirbft_tpu/app/`` — the applied-index + state-machine snapshot is
  written as one atomic blob (tmp + fsync + rename) so a crash between
  "state applied" and "index recorded" cannot double-apply on restart.
  Storage owns the primitive, the app layer is its only caller; a call
  site anywhere else could persist app state without the applied-index
  coupling and silently break exactly-once apply.
- W19 ``mirbft_queue_*`` series names outside ``obsv/bqueue.py`` (and
  the catalog declarations in ``obsv/metrics.py``) — backpressure
  telemetry for bounded hot-path queues flows through the BoundedQueue/
  QueueTelemetry shim only, so every queue reports the same
  depth/wait/saturation semantics; an ad-hoc gauge would fork the
  meaning of "queue depth" per call site and silently bypass the
  saturation accounting the capacity rung attributes against.
- W20 in-place writes through ``NetworkConfig``/``NetworkState``
  objects outside ``core/commitstate.py`` + ``core/actions.py`` — the
  checkpoint-boundary adoption seam is the only place allowed to mutate
  active configuration.  Every other layer builds a fresh object, so a
  committed ``Reconfiguration`` stays the single membership authority;
  a stray ``x.config.field = v`` in an embedder is exactly how two
  nodes end up running divergent configs at the same sequence number.
- W21 raw crypto primitives (``hmac``, ``ed25519_host``, ``bls_host``,
  ``ed25519_batch``) imported outside ``mirbft_tpu/crypto/``,
  ``mirbft_tpu/ops/``, and ``testengine/signing.py`` — key material and
  raw verify/MAC operations are confined so every caller goes through
  the audited seams (``crypto.mac`` LinkAuthenticator, ``crypto.qc``
  vote/aggregate/verify, the signing planes).  A scattered ``hmac.new``
  or direct curve-math call is exactly how a truncation length, a
  domain-separation tag, or a validation step silently diverges between
  two call sites.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding, Rule, register


class _ImportTracker(ast.NodeVisitor):
    """Collect imported names and every name usage per module."""

    def __init__(self):
        self.imports: dict[str, tuple[int, str]] = {}  # name -> (line, what)
        self.used: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            # ``import x as x`` is the conventional re-export idiom: keep.
            if alias.asname is not None and alias.asname == alias.name:
                continue
            self.imports[name] = (node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # compiler directive, not a binding
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            if alias.asname is not None and alias.asname == alias.name:
                continue
            self.imports[name] = (node.lineno, alias.name)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)


def _string_uses(tree: ast.Module) -> set[str]:
    """Names referenced from ``__all__`` string entries (the re-export
    idiom).  Only those assignments count — treating any identifier-shaped
    string anywhere as a use would let a stray dict key mask a genuinely
    unused import."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AugAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        if not any(
            isinstance(t, ast.Name) and t.id == "__all__" for t in targets
        ):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                out.add(sub.value)
    return out


# Path fragments whose files must never read the wall clock: span/metric
# durations and simulated-time code.  testengine/eventlog.py (run metadata
# timestamps) and bench/test files are deliberately outside the scope.
MONOTONIC_ONLY_TREES = (
    "mirbft_tpu/obsv/",
    "mirbft_tpu/core/",
    "mirbft_tpu/runtime/",
    "mirbft_tpu/chaos/",
    "mirbft_tpu/testengine/crypto_plane.py",
    "mirbft_tpu/testengine/signing.py",
)


def in_monotonic_scope(posix: str) -> bool:
    return any(fragment in posix for fragment in MONOTONIC_ONLY_TREES)


def in_exposition_scope(posix: str) -> bool:
    """True for mirbft_tpu files outside obsv/ — where W8 bans
    http.server."""
    return "mirbft_tpu/" in posix and "mirbft_tpu/obsv/" not in posix


# The only files allowed to touch raw sockets: the transport owns
# framing/reconnect/counters, the live chaos driver's partition proxies
# sit deliberately *under* the transport at the socket layer, and the
# app service is the client-facing edge (clients are outside the
# replica-to-replica transport by design — they speak the public KV
# framing, not the node wire protocol).
SOCKET_ALLOWED_FILES = (
    "mirbft_tpu/runtime/transport.py",
    "mirbft_tpu/chaos/live.py",
    "mirbft_tpu/app/service.py",
)


def in_socket_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W9 bans raw ``socket`` imports."""
    return "mirbft_tpu/" in posix and not any(
        posix.endswith(allowed) for allowed in SOCKET_ALLOWED_FILES
    )


# The only files allowed to call os.fsync: the stores own the
# group-commit coalescer, and the app package's durable apply journal
# models an application fsyncing its own state (deliberately outside the
# group-commit path, like a real app would be).  chaos/live.py keeps its
# allowance for historical shims around that journal.
FSYNC_ALLOWED_FILES = (
    "mirbft_tpu/runtime/storage.py",
    "mirbft_tpu/chaos/live.py",
    "mirbft_tpu/app/journal.py",
)

# The one module (and the one helper inside it) allowed to create
# pipeline threads.
THREAD_BAN_FILE = "mirbft_tpu/runtime/processor.py"
THREAD_SPAWN_HELPER = "_spawn_stage"


def in_fsync_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W10 bans ``os.fsync``."""
    return "mirbft_tpu/" in posix and not any(
        posix.endswith(allowed) for allowed in FSYNC_ALLOWED_FILES
    )


# The only tree allowed to manage OS processes: the cluster supervisor
# owns spawn/handshake/kill/restart/teardown for process-per-node runs.
PROCESS_ALLOWED_TREE = "mirbft_tpu/cluster/"

# Modules whose import anywhere else in mirbft_tpu/ trips W11.
PROCESS_MODULES = ("subprocess", "multiprocessing")


def in_process_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W11 bans process-management
    imports."""
    return "mirbft_tpu/" in posix and PROCESS_ALLOWED_TREE not in posix


def in_package_scope(posix: str) -> bool:
    """True for files inside mirbft_tpu/ (W12's scope: tests, tools, and
    bench may use ambient randomness freely)."""
    return "mirbft_tpu/" in posix


# Subpackages holding the adversary machinery: payload-mutation manglers
# (testengine/manglers.py) and frame-rewriting / fault-injection drivers
# (chaos/).  The protocol trees below must never import them — the attack
# harness wraps the protocol, never the reverse.
ADVERSARY_PACKAGES = ("testengine", "chaos")

PROTOCOL_TREES = ("mirbft_tpu/core/", "mirbft_tpu/runtime/")


def in_adversary_ban_scope(posix: str) -> bool:
    """True for files inside the protocol trees W13 protects."""
    return any(tree in posix for tree in PROTOCOL_TREES)


# The only module allowed to introspect process resources (RSS, fd
# counts, rusage): the obsv resource sampler owns the cadence, the gauge
# names, and the leak fit — scattered ad-hoc sampling would fragment all
# three.
RESOURCE_ALLOWED_FILE = "mirbft_tpu/obsv/resources.py"

# Modules whose import anywhere else in mirbft_tpu/ trips W14.
RESOURCE_MODULES = ("resource", "psutil")


def in_resource_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W14 bans process-introspection
    imports."""
    return "mirbft_tpu/" in posix and RESOURCE_ALLOWED_FILE not in posix


# The only places allowed to force device synchronization or open
# profiler sessions: the kernel layer itself and the obsv device
# instrumentation wrapper that times it.
DEVICE_TIMING_ALLOWED_FILE = "mirbft_tpu/obsv/device.py"
DEVICE_TIMING_ALLOWED_TREE = "mirbft_tpu/ops/"


def in_device_timing_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W15 bans ``jax.profiler`` and
    ``block_until_ready``."""
    return (
        "mirbft_tpu/" in posix
        and DEVICE_TIMING_ALLOWED_FILE not in posix
        and DEVICE_TIMING_ALLOWED_TREE not in posix
    )


# The only two files allowed to touch staged snapshot blobs on disk:
# storage.py owns the atomic write/read/remove primitives and
# transfer.py is their single caller (staging verified snapshots for
# crash-resume).  Anyone else handling the staged file would fork the
# atomicity and cleanup discipline.
SNAPSHOT_IO_ALLOWED_FILES = (
    "mirbft_tpu/runtime/storage.py",
    "mirbft_tpu/runtime/transfer.py",
)

# References to these names anywhere else in mirbft_tpu/ trip W17.
SNAPSHOT_IO_FUNCS = (
    "write_snapshot_file",
    "read_snapshot_file",
    "remove_snapshot_file",
)


def in_snapshot_io_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W17 bans snapshot file I/O."""
    return "mirbft_tpu/" in posix and not any(
        posix.endswith(allowed) for allowed in SNAPSHOT_IO_ALLOWED_FILES
    )


# The only places allowed to persist app state: storage.py owns the
# atomic write/read/remove primitives (applied index and state-machine
# snapshot travel as ONE blob) and the app package is their single
# consumer.  A third call site could persist app state without the
# applied-index coupling and break exactly-once apply across restart.
APP_STATE_IO_ALLOWED_FILE = "mirbft_tpu/runtime/storage.py"
APP_STATE_IO_ALLOWED_TREE = "mirbft_tpu/app/"

# References to these names anywhere else in mirbft_tpu/ trip W18.
APP_STATE_IO_FUNCS = (
    "write_app_state",
    "read_app_state",
    "remove_app_state",
)


def in_app_state_io_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W18 bans app-state file I/O."""
    return (
        "mirbft_tpu/" in posix
        and not posix.endswith(APP_STATE_IO_ALLOWED_FILE)
        and APP_STATE_IO_ALLOWED_TREE not in posix
    )


# Raw crypto primitive modules: stdlib hmac (key material flows through
# it) and the host-math references.  Importing any of them outside the
# crypto/ops layers and the engines' signing planes trips W21; everyone
# else authenticates through the audited seams (crypto.mac, crypto.qc,
# the signature planes), which own truncation lengths, domain tags, and
# validation order.
CRYPTO_PRIMITIVE_MODULES = (
    "hmac",
    "ed25519_host",
    "bls_host",
    "ed25519_batch",
)
CRYPTO_PRIMITIVE_ALLOWED_TREES = ("mirbft_tpu/crypto/", "mirbft_tpu/ops/")
CRYPTO_PRIMITIVE_ALLOWED_FILE = "mirbft_tpu/testengine/signing.py"


def in_crypto_primitive_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W21 bans raw-primitive imports."""
    return (
        "mirbft_tpu/" in posix
        and not any(
            tree in posix for tree in CRYPTO_PRIMITIVE_ALLOWED_TREES
        )
        and not posix.endswith(CRYPTO_PRIMITIVE_ALLOWED_FILE)
    )


# The single core/ module allowed to import jax: the device-resident ack
# plane (dense bitmask state + popcount quorum kernels).  Everything else
# in core/ is the purity auditor's deterministic root set.
CORE_JAX_ALLOWED_FILE = "mirbft_tpu/core/device_tracker.py"


# The only emission point for bounded-queue backpressure series: the
# BoundedQueue/QueueTelemetry shim.  metrics.py is allowed too — the
# catalog must declare the family names as literals.
QUEUE_SERIES_PREFIX = "mirbft_queue_"
QUEUE_SERIES_ALLOWED_FILES = (
    "mirbft_tpu/obsv/bqueue.py",
    "mirbft_tpu/obsv/metrics.py",
)


def in_queue_series_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W19 bans mirbft_queue_* literals."""
    return "mirbft_tpu/" in posix and not any(
        posix.endswith(allowed) for allowed in QUEUE_SERIES_ALLOWED_FILES
    )


def in_core_jax_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu/core/ files where W16 bans jax imports."""
    return "mirbft_tpu/core/" in posix and CORE_JAX_ALLOWED_FILE not in posix


# The adoption seam: the only files allowed to mutate the innards of a
# NetworkConfig/NetworkState in place.  commitstate.py owns config
# activation (next_network_config / the reconfigured-checkpoint flip)
# and actions.py owns CheckpointResult construction.  Everyone else must
# build a fresh pb.NetworkConfig/pb.NetworkState — a stray in-place edit
# outside the seam is exactly how two nodes end up running divergent
# configs at the same sequence number.
CONFIG_MUTATION_ALLOWED_FILES = (
    "mirbft_tpu/core/commitstate.py",
    "mirbft_tpu/core/actions.py",
)

# Attribute bases whose fields must not be assigned outside the seam.
CONFIG_MUTATION_BASES = frozenset(
    {"config", "network_config", "network_state", "active_state"}
)


def in_config_mutation_ban_scope(posix: str) -> bool:
    """True for mirbft_tpu files where W20 confines config mutation."""
    return "mirbft_tpu/" in posix and not any(
        posix.endswith(allowed) for allowed in CONFIG_MUTATION_ALLOWED_FILES
    )


def _spawn_helper_spans(tree: ast.Module) -> list[tuple[int, int]]:
    """Line spans of every ``_spawn_stage`` definition (the only place
    W10 permits ``threading.Thread(...)`` in the processor module)."""
    return [
        (node.lineno, node.end_lineno or node.lineno)
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name == THREAD_SPAWN_HELPER
    ]


# -- per-rule checkers -------------------------------------------------------


def _check_w1(ctx: FileContext):
    tracker = _ImportTracker()
    tracker.visit(ctx.tree)
    stringy = _string_uses(ctx.tree)
    if ctx.path.name == "__init__.py":
        return  # package __init__ imports are the public surface
    for name, (line, what) in sorted(tracker.imports.items()):
        if name in tracker.used or name in stringy:
            continue
        yield Finding("W1", ctx.path, line, f"unused import '{what}'")


def _check_w2(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield Finding("W2", ctx.path, node.lineno, "bare 'except:'")


def _check_w3(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assert) and isinstance(node.test, ast.Tuple):
            if node.test.elts:
                yield Finding(
                    "W3", ctx.path, node.lineno, "assert on tuple is always true"
                )


def _check_w4(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Compare):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Is, ast.IsNot)) and isinstance(
                comp, ast.Constant
            ) and isinstance(comp.value, (str, int, bytes)) and not isinstance(
                comp.value, bool
            ):
                yield Finding(
                    "W4", ctx.path, node.lineno, "'is' comparison with literal"
                )


def _check_w5(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                yield Finding(
                    "W5", ctx.path, default.lineno, "mutable default argument"
                )


def _check_w6(ctx: FileContext):
    # Format specs (the ``:6d`` in an f-string) are themselves JoinedStr
    # nodes; they must not trip the empty-f-string check.
    spec_ids = {
        id(n.format_spec)
        for n in ast.walk(ctx.tree)
        if isinstance(n, ast.FormattedValue) and n.format_spec is not None
    }
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.JoinedStr) and id(node) not in spec_ids:
            if not any(isinstance(v, ast.FormattedValue) for v in node.values):
                yield Finding(
                    "W6", ctx.path, node.lineno, "f-string without placeholders"
                )


def check_w7(ctx: FileContext):
    """Exposed for the shim's ``monotonic_only`` forcing (scope is applied
    by the registry in normal runs)."""
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "time"
            and isinstance(node.value, ast.Name)
            and node.value.id == "time"
        ):
            yield Finding(
                "W7",
                ctx.path,
                node.lineno,
                "wall-clock time.time() in monotonic-only code "
                "(use time.perf_counter)",
            )
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            if any(alias.name == "time" for alias in node.names):
                yield Finding(
                    "W7",
                    ctx.path,
                    node.lineno,
                    "'from time import time' in monotonic-only code "
                    "(use time.perf_counter)",
                )


def _check_w8(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        hit = False
        if isinstance(node, ast.Import):
            hit = any(
                alias.name == "http.server"
                or alias.name.startswith("http.server.")
                for alias in node.names
            )
        elif isinstance(node, ast.ImportFrom):
            hit = node.module is not None and (
                node.module == "http.server"
                or node.module.startswith("http.server.")
                or (
                    node.module == "http"
                    and any(alias.name == "server" for alias in node.names)
                )
            )
        if hit:
            yield Finding(
                "W8",
                ctx.path,
                node.lineno,
                "http.server outside obsv/ (exposition must go through "
                "obsv.exporter and the catalog renderer)",
            )


def _check_w9(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        hit = False
        if isinstance(node, ast.Import):
            hit = any(
                alias.name == "socket" or alias.name.startswith("socket.")
                for alias in node.names
            )
        elif isinstance(node, ast.ImportFrom):
            hit = node.module is not None and (
                node.module == "socket" or node.module.startswith("socket.")
            )
        if hit:
            yield Finding(
                "W9",
                ctx.path,
                node.lineno,
                "raw socket outside runtime/transport.py and chaos/live.py "
                "(wire I/O goes through the transport or the live driver's "
                "partition proxies)",
            )


def _check_w10_fsync(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        hit = (
            isinstance(node, ast.Attribute)
            and node.attr == "fsync"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        ) or (
            isinstance(node, ast.ImportFrom)
            and node.module == "os"
            and any(alias.name == "fsync" for alias in node.names)
        )
        if hit:
            yield Finding(
                "W10",
                ctx.path,
                node.lineno,
                "os.fsync outside runtime/storage.py (durability goes "
                "through the stores' sync()/sync_token() group-commit API)",
            )


def _check_w10_thread(ctx: FileContext):
    if not ctx.posix.endswith(THREAD_BAN_FILE):
        return
    spawn_spans = _spawn_helper_spans(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = (
            isinstance(func, ast.Attribute)
            and func.attr == "Thread"
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading"
        ) or (isinstance(func, ast.Name) and func.id == "Thread")
        if hit and not any(lo <= node.lineno <= hi for lo, hi in spawn_spans):
            yield Finding(
                "W10",
                ctx.path,
                node.lineno,
                "raw threading.Thread in runtime/processor.py outside "
                "_spawn_stage (stage threads go through the single "
                "creation point)",
            )


def _check_w10(ctx: FileContext):
    if in_fsync_ban_scope(ctx.posix):
        yield from _check_w10_fsync(ctx)
    yield from _check_w10_thread(ctx)


def _check_w11(ctx: FileContext):
    prefixes = tuple(m + "." for m in PROCESS_MODULES)
    for node in ast.walk(ctx.tree):
        hit = False
        if isinstance(node, ast.Import):
            hit = any(
                alias.name in PROCESS_MODULES
                or alias.name.startswith(prefixes)
                for alias in node.names
            )
        elif isinstance(node, ast.ImportFrom):
            hit = node.module is not None and (
                node.module in PROCESS_MODULES
                or node.module.startswith(prefixes)
            )
        if hit:
            yield Finding(
                "W11",
                ctx.path,
                node.lineno,
                "subprocess/multiprocessing outside cluster/ (process "
                "lifecycle goes through the cluster supervisor)",
            )


def _check_w14(ctx: FileContext):
    prefixes = tuple(m + "." for m in RESOURCE_MODULES)
    for node in ast.walk(ctx.tree):
        hit = False
        if isinstance(node, ast.Import):
            hit = any(
                alias.name in RESOURCE_MODULES
                or alias.name.startswith(prefixes)
                for alias in node.names
            )
        elif isinstance(node, ast.ImportFrom):
            hit = node.module is not None and (
                node.module in RESOURCE_MODULES
                or node.module.startswith(prefixes)
            )
        if hit:
            yield Finding(
                "W14",
                ctx.path,
                node.lineno,
                "resource/psutil outside obsv/resources.py (process "
                "introspection goes through the obsv resource sampler)",
            )


def _check_w21(ctx: FileContext):
    def primitive_in(dotted: str) -> str | None:
        for part in dotted.split("."):
            if part in CRYPTO_PRIMITIVE_MODULES:
                return part
        return None

    for node in ast.walk(ctx.tree):
        hits = []
        if isinstance(node, ast.Import):
            hits = [
                name
                for alias in node.names
                if (name := primitive_in(alias.name)) is not None
            ]
        elif isinstance(node, ast.ImportFrom):
            name = primitive_in(node.module or "")
            if name is not None:
                hits = [name]
            else:
                hits = [
                    alias.name
                    for alias in node.names
                    if alias.name in CRYPTO_PRIMITIVE_MODULES
                ]
        for name in hits:
            yield Finding(
                "W21",
                ctx.path,
                node.lineno,
                f"raw crypto primitive '{name}' outside crypto//ops//"
                "testengine/signing.py (authenticate through crypto.mac, "
                "crypto.qc, or the signing planes)",
            )


def _check_w15(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Attribute):
            if node.attr == "block_until_ready":
                yield Finding(
                    "W15",
                    ctx.path,
                    node.lineno,
                    "block_until_ready outside obsv/device.py and ops/ "
                    "(device sync serializes the pipeline; time kernels "
                    "through obsv.device.instrument)",
                )
            elif (
                node.attr == "profiler"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax"
            ):
                yield Finding(
                    "W15",
                    ctx.path,
                    node.lineno,
                    "jax.profiler outside obsv/device.py and ops/ "
                    "(profiler sessions are confined to the device "
                    "instrumentation layer)",
                )
        elif isinstance(node, ast.Import):
            if any(
                alias.name == "jax.profiler"
                or alias.name.startswith("jax.profiler.")
                for alias in node.names
            ):
                yield Finding(
                    "W15",
                    ctx.path,
                    node.lineno,
                    "jax.profiler outside obsv/device.py and ops/ "
                    "(profiler sessions are confined to the device "
                    "instrumentation layer)",
                )
        elif isinstance(node, ast.ImportFrom):
            if node.module is not None and (
                node.module == "jax.profiler"
                or node.module.startswith("jax.profiler.")
            ):
                yield Finding(
                    "W15",
                    ctx.path,
                    node.lineno,
                    "jax.profiler outside obsv/device.py and ops/ "
                    "(profiler sessions are confined to the device "
                    "instrumentation layer)",
                )


# random attributes that do NOT carry module-global RNG state.
_RANDOM_ALLOWED_ATTRS = {"Random"}


def check_w12(ctx: FileContext):
    """Unseeded-randomness ban.  Allowed spellings: ``random.Random(...)``
    instance construction (seed it for anything protocol-visible) and the
    explicitly keyed ``jax.random`` API.  Everything else — the ``random``
    module's global functions, ``random.SystemRandom``, and the whole
    ``numpy.random`` legacy global-state API — draws from state no seed in
    this repo controls."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Attribute):
            base = node.value
            if (
                isinstance(base, ast.Name)
                and base.id == "random"
                and node.attr not in _RANDOM_ALLOWED_ATTRS
            ):
                yield Finding(
                    "W12",
                    ctx.path,
                    node.lineno,
                    f"unseeded random.{node.attr} (module-global RNG "
                    "state; use a seeded random.Random(seed) instance or "
                    "jax.random keys)",
                )
            if (
                isinstance(base, ast.Attribute)
                and base.attr == "random"
                and isinstance(base.value, ast.Name)
                and base.value.id in ("np", "numpy")
            ):
                yield Finding(
                    "W12",
                    ctx.path,
                    node.lineno,
                    f"numpy.random.{node.attr} legacy global state (use a "
                    "seeded random.Random(seed) instance or jax.random "
                    "keys)",
                )
        elif isinstance(node, ast.ImportFrom):
            if node.module == "random":
                for alias in node.names:
                    if alias.name not in _RANDOM_ALLOWED_ATTRS:
                        yield Finding(
                            "W12",
                            ctx.path,
                            node.lineno,
                            f"'from random import {alias.name}' (module-"
                            "global RNG state; use a seeded "
                            "random.Random(seed) instance)",
                        )
            elif node.module is not None and (
                node.module == "numpy.random"
                or node.module.startswith("numpy.random.")
            ):
                yield Finding(
                    "W12",
                    ctx.path,
                    node.lineno,
                    "numpy.random legacy global state (use a seeded "
                    "random.Random(seed) instance or jax.random keys)",
                )
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "numpy.random" or alias.name.startswith(
                    "numpy.random."
                ):
                    yield Finding(
                        "W12",
                        ctx.path,
                        node.lineno,
                        "numpy.random legacy global state (use a seeded "
                        "random.Random(seed) instance or jax.random keys)",
                    )


def _adversary_package(node: ast.AST) -> str | None:
    """The banned subpackage an import statement reaches, or None.

    Catches every spelling: ``import mirbft_tpu.chaos.live``,
    ``from mirbft_tpu.testengine.manglers import rule``,
    ``from mirbft_tpu import chaos``, and the relative forms
    ``from ..chaos import x`` / ``from .. import testengine`` that
    core/runtime modules would actually write."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            for pkg in ADVERSARY_PACKAGES:
                full = f"mirbft_tpu.{pkg}"
                if alias.name == full or alias.name.startswith(full + "."):
                    return pkg
        return None
    if not isinstance(node, ast.ImportFrom):
        return None
    module = node.module or ""
    if node.level == 0:
        for pkg in ADVERSARY_PACKAGES:
            full = f"mirbft_tpu.{pkg}"
            if module == full or module.startswith(full + "."):
                return pkg
        if module == "mirbft_tpu":
            for alias in node.names:
                if alias.name in ADVERSARY_PACKAGES:
                    return alias.name
        return None
    # Relative import from inside the package.
    head = module.split(".", 1)[0]
    if head in ADVERSARY_PACKAGES:
        return head
    if not module:
        for alias in node.names:
            if alias.name in ADVERSARY_PACKAGES:
                return alias.name
    return None


def _check_w13(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        pkg = _adversary_package(node)
        if pkg is not None:
            yield Finding(
                "W13",
                ctx.path,
                node.lineno,
                f"adversary tooling mirbft_tpu.{pkg} imported from "
                "core/runtime (payload mutation and frame rewriting live "
                "in testengine/ and chaos/; the harness wraps the "
                "protocol, never the reverse)",
            )


def _check_w16(ctx: FileContext):
    msg = (
        "jax import inside mirbft_tpu/core/ outside core/device_tracker.py "
        "(the protocol state machine is pure deterministic Python; the "
        "device ack plane is the single sanctioned accelerator boundary)"
    )
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax" or alias.name.startswith("jax."):
                    yield Finding("W16", ctx.path, node.lineno, msg)
                    break
        elif isinstance(node, ast.ImportFrom):
            module = node.module or ""
            if node.level == 0 and (
                module == "jax" or module.startswith("jax.")
            ):
                yield Finding("W16", ctx.path, node.lineno, msg)


def _check_w17(ctx: FileContext):
    msg = (
        "snapshot file I/O outside runtime/storage.py and "
        "runtime/transfer.py (the staged-blob crash contract — atomic "
        "write, restart resume, cleanup — lives in exactly two files; "
        "everything else goes through the TransferEngine)"
    )
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            if any(alias.name in SNAPSHOT_IO_FUNCS for alias in node.names):
                yield Finding("W17", ctx.path, node.lineno, msg)
        elif isinstance(node, ast.Name):
            if node.id in SNAPSHOT_IO_FUNCS:
                yield Finding("W17", ctx.path, node.lineno, msg)
        elif isinstance(node, ast.Attribute):
            if node.attr in SNAPSHOT_IO_FUNCS:
                yield Finding("W17", ctx.path, node.lineno, msg)


def _check_w18(ctx: FileContext):
    msg = (
        "app-state file I/O outside runtime/storage.py and mirbft_tpu/app/ "
        "(the applied index and the state-machine snapshot are persisted "
        "as one atomic blob; storage owns the primitive and the app layer "
        "is its only caller — anything else risks double-apply after a "
        "crash)"
    )
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            if any(alias.name in APP_STATE_IO_FUNCS for alias in node.names):
                yield Finding("W18", ctx.path, node.lineno, msg)
        elif isinstance(node, ast.Name):
            if node.id in APP_STATE_IO_FUNCS:
                yield Finding("W18", ctx.path, node.lineno, msg)
        elif isinstance(node, ast.Attribute):
            if node.attr in APP_STATE_IO_FUNCS:
                yield Finding("W18", ctx.path, node.lineno, msg)


def _check_w19(ctx: FileContext):
    msg = (
        "mirbft_queue_* series emitted outside the obsv/bqueue.py shim "
        "(bounded-queue depth/wait/saturation telemetry must flow "
        "through BoundedQueue/QueueTelemetry so every queue shares the "
        "same semantics; an ad-hoc gauge bypasses the saturation "
        "accounting the capacity rung attributes against)"
    )
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith(QUEUE_SERIES_PREFIX)
        ):
            yield Finding("W19", ctx.path, node.lineno, msg)


def _config_mutation_hit(target) -> bool:
    """True when an assignment target writes *through* a config/state
    object — ``x.config.checkpoint_interval = v``,
    ``self.active_state.reconfigured = True``,
    ``state.network_config.nodes[i] = v`` — as opposed to rebinding a
    plain attribute (``self.network_state = fresh`` stays legal: handing
    out a new object is how everyone *outside* the seam is supposed to
    change configuration)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        return any(_config_mutation_hit(elt) for elt in target.elts)
    if isinstance(target, ast.Subscript):
        target = target.value
    if not isinstance(target, ast.Attribute):
        return False
    value = target.value
    while True:
        if isinstance(value, ast.Attribute):
            if value.attr in CONFIG_MUTATION_BASES:
                return True
            value = value.value
        elif isinstance(value, ast.Name):
            return value.id in CONFIG_MUTATION_BASES
        else:
            return False


def _check_w20(ctx: FileContext):
    msg = (
        "NetworkConfig/NetworkState mutated outside the adoption seam "
        "(core/commitstate.py + core/actions.py own in-place config "
        "changes; everywhere else must construct a fresh "
        "pb.NetworkConfig/pb.NetworkState — an in-place edit here can "
        "diverge the active config across nodes)"
    )
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        else:
            continue
        if any(_config_mutation_hit(target) for target in targets):
            yield Finding("W20", ctx.path, node.lineno, msg)


def _as_list(gen_fn):
    def check(ctx):
        return list(gen_fn(ctx))

    return check


register(
    Rule(
        id="W1",
        title="unused import",
        doc="Dead import seams hide refactor mistakes.",
        check=_as_list(_check_w1),
        severity="warning",
    )
)
register(
    Rule(
        id="W2",
        title="bare except",
        doc="A bare `except:` swallows KeyboardInterrupt and SystemExit.",
        check=_as_list(_check_w2),
        severity="warning",
    )
)
register(
    Rule(
        id="W3",
        title="assert on tuple",
        doc="`assert (x, 'msg')` is always true — a silently-disabled check.",
        check=_as_list(_check_w3),
        severity="warning",
    )
)
register(
    Rule(
        id="W4",
        title="is-comparison with literal",
        doc="`is` against a str/int literal tests identity, not equality.",
        check=_as_list(_check_w4),
        severity="warning",
    )
)
register(
    Rule(
        id="W5",
        title="mutable default argument",
        doc="Mutable defaults are shared across calls — a shared-state bug factory.",
        check=_as_list(_check_w5),
        severity="warning",
    )
)
register(
    Rule(
        id="W6",
        title="f-string without placeholders",
        doc="Usually a forgotten interpolation.",
        check=_as_list(_check_w6),
        severity="warning",
    )
)
register(
    Rule(
        id="W7",
        title="wall clock in monotonic-only code",
        doc=(
            "Instrumented / latency-measuring paths must use "
            "time.perf_counter — the wall clock steps under NTP and breaks "
            "span nesting and histograms."
        ),
        check=_as_list(check_w7),
        scope=in_monotonic_scope,
    )
)
register(
    Rule(
        id="W8",
        title="http.server outside obsv/",
        doc=(
            "Metric/status exposition must go through the obsv exporter "
            "and its catalog renderer."
        ),
        check=_as_list(_check_w8),
        scope=in_exposition_scope,
    )
)
register(
    Rule(
        id="W9",
        title="raw socket outside the transport",
        doc=(
            "All wire I/O flows through runtime/transport.py or the live "
            "chaos driver's partition proxies."
        ),
        check=_as_list(_check_w9),
        scope=in_socket_ban_scope,
    )
)
register(
    Rule(
        id="W10",
        title="durability/pipeline discipline",
        doc=(
            "os.fsync is confined to the stores' group-commit coalescer; "
            "processor stage threads go through _spawn_stage."
        ),
        check=_as_list(_check_w10),
        scope=lambda posix: "mirbft_tpu/" in posix,
    )
)
register(
    Rule(
        id="W11",
        title="process management outside cluster/",
        doc=(
            "subprocess/multiprocessing are confined to the cluster "
            "supervisor's lifecycle machinery."
        ),
        check=_as_list(_check_w11),
        scope=in_process_ban_scope,
    )
)
register(
    Rule(
        id="W13",
        title="adversary tooling imported from core/runtime",
        doc=(
            "Payload-mutation and frame-rewriting helpers are confined to "
            "testengine/ and chaos/; the protocol trees must not import "
            "their own attack harness."
        ),
        check=_as_list(_check_w13),
        scope=in_adversary_ban_scope,
    )
)
register(
    Rule(
        id="W12",
        title="unseeded randomness",
        doc=(
            "Unseeded random.* module functions and numpy.random legacy "
            "global state are banned in mirbft_tpu/; seeded "
            "random.Random(seed) instances and jax.random keys only."
        ),
        check=_as_list(check_w12),
        scope=in_package_scope,
    )
)
register(
    Rule(
        id="W14",
        title="resource introspection outside obsv/resources.py",
        doc=(
            "resource/psutil process-introspection imports are confined "
            "to the obsv resource sampler so cadence, gauge names, and "
            "leak fits stay in one place."
        ),
        check=_as_list(_check_w14),
        scope=in_resource_ban_scope,
    )
)
register(
    Rule(
        id="W15",
        title="device sync/profiler outside the kernel layer",
        doc=(
            "jax.profiler and block_until_ready are confined to "
            "mirbft_tpu/obsv/device.py and mirbft_tpu/ops/; protocol "
            "code must not force device synchronization or open "
            "profiler sessions."
        ),
        check=_as_list(_check_w15),
        scope=in_device_timing_ban_scope,
    )
)
register(
    Rule(
        id="W17",
        title="snapshot file I/O outside storage.py/transfer.py",
        doc=(
            "write_snapshot_file/read_snapshot_file/remove_snapshot_file "
            "are confined to runtime/storage.py (the atomic primitives) "
            "and runtime/transfer.py (their single caller); a third call "
            "site would fork the staged-blob crash contract."
        ),
        check=_as_list(_check_w17),
        scope=in_snapshot_io_ban_scope,
    )
)
register(
    Rule(
        id="W18",
        title="app-state file I/O outside storage.py/app/",
        doc=(
            "write_app_state/read_app_state/remove_app_state are confined "
            "to runtime/storage.py (the atomic applied-index + snapshot "
            "blob primitives) and mirbft_tpu/app/ (their single consumer); "
            "a third call site could persist app state without the "
            "applied-index coupling and break exactly-once apply."
        ),
        check=_as_list(_check_w18),
        scope=in_app_state_io_ban_scope,
    )
)
register(
    Rule(
        id="W19",
        title="mirbft_queue_* series outside the bqueue shim",
        doc=(
            "Bounded hot-path queue telemetry (mirbft_queue_depth / "
            "mirbft_queue_wait_seconds / mirbft_queue_saturated_total) is "
            "emitted only by obsv/bqueue.py (metrics.py may declare the "
            "names in the catalog); every queue must share the shim's "
            "depth/wait/saturation semantics rather than minting ad-hoc "
            "gauges."
        ),
        check=_as_list(_check_w19),
        scope=in_queue_series_ban_scope,
    )
)
register(
    Rule(
        id="W20",
        title="config mutation outside the adoption seam",
        doc=(
            "In-place writes through NetworkConfig/NetworkState objects "
            "(x.config.field = v, self.active_state.reconfigured = True) "
            "are confined to core/commitstate.py and core/actions.py — "
            "the checkpoint-boundary adoption seam.  Every other layer "
            "changes configuration by constructing a fresh object, so a "
            "committed Reconfiguration stays the single membership "
            "authority and no embedder can locally fork the active "
            "config."
        ),
        check=_as_list(_check_w20),
        scope=in_config_mutation_ban_scope,
    )
)
register(
    Rule(
        id="W21",
        title="raw crypto primitives outside the crypto layer",
        doc=(
            "hmac / ed25519_host / bls_host / ed25519_batch imports are "
            "confined to mirbft_tpu/crypto/, mirbft_tpu/ops/, and "
            "testengine/signing.py; every other layer authenticates "
            "through the audited seams (crypto.mac LinkAuthenticator, "
            "crypto.qc vote/aggregate/verify, the signing planes) so "
            "truncation lengths, domain tags, and validation order "
            "cannot diverge between call sites."
        ),
        check=_as_list(_check_w21),
        scope=in_crypto_primitive_ban_scope,
    )
)
register(
    Rule(
        id="W16",
        title="jax import in core/ outside device_tracker.py",
        doc=(
            "mirbft_tpu/core/ is pure deterministic Python; jax/jnp "
            "imports are confined to core/device_tracker.py, the single "
            "sanctioned accelerator boundary of the protocol."
        ),
        check=_as_list(_check_w16),
        scope=in_core_jax_ban_scope,
    )
)
