"""Dynamic lock-order harness — the stand-in for ``go test -race``.

A :class:`LockMonitor` hands out instrumented Lock/RLock/Condition
objects (via a ``threading``-compatible proxy module that tests
monkeypatch into the modules under test).  Every acquisition records,
per thread, the set of locks already held; the cross-thread union of
those (held, acquired) pairs is the lock-acquisition graph.  A cycle in
that graph is a potential deadlock even if this particular run never
interleaved into it — exactly the class of bug a single green test run
cannot rule out.

Identity is the lock's *creation site* (file:line), not the instance:
the transport creates one Condition per peer channel from the same
line, and "channel A held while acquiring channel B" must aggregate to
one node for the ordering to mean anything.  The flip side: self-edges
(same-site lock while holding a same-site lock) are skipped, since
distinct instances from one site are indistinguishable here — a
same-site ordering protocol cannot be validated by this harness and
must be argued in code review instead.

Condition ``wait()`` releases and reacquires its lock; the reacquire is
not a fresh ordered acquisition (the thread already owned the lock when
it called wait), so it restores held-state without recording edges.

Usage (see tests/test_pipeline.py / tests/test_cluster.py):

    monitor = LockMonitor()
    proxy = monitor.threading_proxy()
    monkeypatch.setattr(processor_module, "threading", proxy)
    ... exercise the system ...
    monitor.assert_no_cycles()
"""

from __future__ import annotations

import threading
import traceback


class LockOrderViolation(AssertionError):
    """A cycle in the cross-thread lock-acquisition graph."""


def _creation_site() -> str:
    """file:line of the caller that constructed the lock, skipping
    frames inside this module."""
    for frame in reversed(traceback.extract_stack()):
        if frame.filename != __file__:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


class LockMonitor:
    def __init__(self):
        self._meta = threading.Lock()  # guards _edges only
        self._local = threading.local()
        # (held site, acquired site) -> witness description
        self._edges: dict[tuple[str, str], str] = {}

    # -- recording -----------------------------------------------------------

    def _held(self) -> list[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _note_acquired(self, site: str, record_edges: bool = True) -> None:
        held = self._held()
        if record_edges and site not in held:
            thread = threading.current_thread().name
            with self._meta:
                for prior in held:
                    if prior != site:  # same-site: see module docstring
                        self._edges.setdefault(
                            (prior, site), f"thread {thread}"
                        )
        held.append(site)

    def _note_released(self, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    # -- graph ---------------------------------------------------------------

    def edges(self) -> dict[tuple[str, str], str]:
        with self._meta:
            return dict(self._edges)

    def find_cycle(self) -> list[str] | None:
        """A list of sites forming a cycle (first == last), or None."""
        edges = self.edges()
        graph: dict[str, list[str]] = {}
        for a, b in edges:
            graph.setdefault(a, []).append(b)
        WHITE, GREY, BLACK = 0, 1, 2
        color = {node: WHITE for node in graph}
        path: list[str] = []

        def dfs(node: str) -> list[str] | None:
            color[node] = GREY
            path.append(node)
            for nxt in sorted(graph.get(node, ())):
                if color.get(nxt, WHITE) == GREY:
                    return path[path.index(nxt) :] + [nxt]
                if color.get(nxt, WHITE) == WHITE:
                    color[nxt] = WHITE
                    found = dfs(nxt)
                    if found is not None:
                        return found
            path.pop()
            color[node] = BLACK
            return None

        for node in sorted(graph):
            if color.get(node, WHITE) == WHITE:
                found = dfs(node)
                if found is not None:
                    return found
        return None

    def assert_no_cycles(self) -> None:
        cycle = self.find_cycle()
        if cycle is None:
            return
        edges = self.edges()
        lines = [
            "lock-order cycle (potential deadlock):",
        ]
        for a, b in zip(cycle, cycle[1:]):
            lines.append(f"  {a} held while acquiring {b} ({edges[a, b]})")
        raise LockOrderViolation("\n".join(lines))

    # -- instrumented primitives --------------------------------------------

    def Lock(self):
        return _InstrumentedLock(self, threading.Lock(), _creation_site())

    def RLock(self):
        return _InstrumentedLock(self, threading.RLock(), _creation_site())

    def Condition(self, lock=None):
        if isinstance(lock, _InstrumentedLock):
            inner = threading.Condition(lock._inner)
            site = lock._site  # holding the cv IS holding the lock
        else:
            inner = threading.Condition(lock)
            site = _creation_site()
        return _InstrumentedCondition(self, inner, site)

    def threading_proxy(self):
        """A ``threading``-shaped namespace whose Lock/RLock/Condition
        are instrumented; everything else (Thread, Event, local, ...)
        forwards to the real module."""
        return _ThreadingProxy(self)


class _InstrumentedLock:
    def __init__(self, monitor: LockMonitor, inner, site: str):
        self._monitor = monitor
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._monitor._note_acquired(self._site)
        return got

    def release(self) -> None:
        self._inner.release()
        self._monitor._note_released(self._site)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _InstrumentedCondition:
    def __init__(self, monitor: LockMonitor, inner, site: str):
        self._monitor = monitor
        self._inner = inner
        self._site = site

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._monitor._note_acquired(self._site)
        return got

    def release(self) -> None:
        self._inner.release()
        self._monitor._note_released(self._site)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout=None):
        self._monitor._note_released(self._site)
        try:
            return self._inner.wait(timeout)
        finally:
            # reacquisition of a lock we already owned: no new edges
            self._monitor._note_acquired(self._site, record_edges=False)

    def wait_for(self, predicate, timeout=None):
        self._monitor._note_released(self._site)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._monitor._note_acquired(self._site, record_edges=False)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


class _ThreadingProxy:
    def __init__(self, monitor: LockMonitor):
        self._monitor = monitor

    def Lock(self):
        return self._monitor.Lock()

    def RLock(self):
        return self._monitor.RLock()

    def Condition(self, lock=None):
        return self._monitor.Condition(lock)

    def __getattr__(self, name: str):
        return getattr(threading, name)
