"""mirbft-tpu static-analysis suite.

The reference CI runs staticcheck + the Go race detector on every build
(reference: .travis.yml:16-18).  This package is that discipline rebuilt
for the Python port, stdlib-only, in three layers:

- ``engine``   — the rule registry, per-line suppressions, the committed
  baseline, and machine-readable (``--json``) output.
- ``rules_w``  — general defect classes (W1..W12): the original
  tools/lint.py checks as Rule objects plus the seeded-randomness ban.
- ``rules_d``  — the determinism purity auditor (D1xx): an import graph
  over ``mirbft_tpu/`` proving that ``core/`` and the deterministic
  testengine paths never transitively reach an impure effect (clocks,
  unseeded randomness, I/O, threading, env, ``id()``, set iteration
  feeding ordered state), modulo a documented allowlist.
- ``rules_c``  — the concurrency checker (C2xx): the ``# guarded-by:``
  annotation convention on shared attributes, statically enforced.
- ``lockorder`` — the dynamic half of the race story: instrumented locks
  recording the cross-thread acquisition graph and failing on order
  cycles (the stand-in for ``go test -race``), wired into the
  pipeline/transport/cluster tier-1 tests.

``tools/lint.py`` remains the CLI entry point (a thin shim over this
package).  Policy and the rule catalog live in docs/ANALYSIS.md.
"""

from .engine import (  # noqa: F401
    Finding,
    FileContext,
    Rule,
    REGISTRY,
    all_rules,
    load_baseline,
    run,
    to_json,
)
