"""Concurrency checker (C2xx): the ``# guarded-by:`` convention.

Annotation forms (docs/ANALYSIS.md):

    self._inflight = 0  # guarded-by: _mutex
        Every later load/store of ``self._inflight`` must sit lexically
        inside ``with self._mutex:`` (or a Condition aliasing it).

    def _write_locked(self, ...):  # holds: _lock
        The method requires the lock held by its caller: its body is
        exempt from C201 for that lock, and every call site must itself
        sit inside ``with <that lock>`` (C202).

Conventions the checker understands:

- Condition aliases are auto-detected: ``self._cv =
  threading.Condition(self._mutex)`` makes ``with self._cv:`` satisfy
  guards on ``_mutex`` and vice versa.
- ``__init__`` bodies are exempt for their own ``self.*`` attributes —
  the object is not yet shared during construction.
- Cross-object access: ``peer._attr`` guarded by ``_lock`` is satisfied
  by ``with peer._lock:`` — the *same base expression* must hold the
  lock (matched structurally, so aliasing through a different variable
  is conservatively flagged).
- A nested ``def``/``lambda`` does not inherit the enclosing ``with``:
  closures run later, on whichever thread calls them.

The checker is annotation-driven: files without annotations produce no
findings, so it is safe to run repo-wide.  It is lexical, not a race
detector — the dynamic half (lock-order cycles) lives in
``tools/analysis/lockorder.py``.
"""

from __future__ import annotations

import ast
import re

from .engine import FileContext, Finding, Rule, register

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?:self\.)?([A-Za-z_]\w*)")
_HOLDS_RE = re.compile(r"#\s*holds:\s*(?:self\.)?([A-Za-z_]\w*)")


class _ClassModel:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.guarded: dict[str, str] = {}  # attr -> canonical lock
        self.aliases: dict[str, str] = {}  # cv name -> wrapped lock name
        self.holds: dict[str, set[str]] = {}  # method -> canonical locks
        self.self_attrs: set[str] = set()  # every self.X ever assigned

    def canonical(self, lock: str) -> str:
        seen = set()
        while lock in self.aliases and lock not in seen:
            seen.add(lock)
            lock = self.aliases[lock]
        return lock


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_class(ctx: FileContext, node: ast.ClassDef) -> _ClassModel:
    model = _ClassModel(node)
    annots: dict[int, str] = {}
    holds_annots: dict[int, str] = {}
    end = node.end_lineno or node.lineno
    for lineno in range(node.lineno, end + 1):
        line = ctx.lines[lineno - 1] if lineno - 1 < len(ctx.lines) else ""
        match = _GUARDED_RE.search(line)
        if match:
            annots[lineno] = match.group(1)
        match = _HOLDS_RE.search(line)
        if match:
            holds_annots[lineno] = match.group(1)

    for sub in ast.walk(node):
        if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                sub.targets if isinstance(sub, ast.Assign) else [sub.target]
            )
            for target in targets:
                attr = _self_attr(target)
                if attr is None:
                    continue
                model.self_attrs.add(attr)
                # A multi-line assignment carries its annotation on the
                # closing line.
                lock = annots.get(sub.lineno) or annots.get(
                    sub.end_lineno or sub.lineno
                )
                if lock is not None:
                    model.guarded[attr] = lock
            # threading.Condition(self.X) alias detection
            value = getattr(sub, "value", None)
            if (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "Condition"
                and value.args
            ):
                wrapped = _self_attr(value.args[0])
                for target in targets:
                    cv = _self_attr(target)
                    if cv is not None and wrapped is not None:
                        model.aliases[cv] = wrapped
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lock = holds_annots.get(sub.lineno)
            if lock is not None:
                model.holds.setdefault(sub.name, set()).add(lock)
    # canonicalize holds and guards through the alias map
    model.holds = {
        name: {model.canonical(lock) for lock in locks}
        for name, locks in model.holds.items()
    }
    model.guarded = {
        attr: model.canonical(lock) for attr, lock in model.guarded.items()
    }
    return model


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking lexically-held locks."""

    def __init__(
        self,
        ctx: FileContext,
        model: _ClassModel,
        file_guarded: dict[str, set[str]],
        method: ast.FunctionDef,
    ):
        self.ctx = ctx
        self.model = model
        self.file_guarded = file_guarded
        self.method = method
        self.is_init = method.name == "__init__"
        self.method_holds = model.holds.get(method.name, set())
        # (base ast.dump, canonical lock name) currently held lexically
        self.held: set[tuple[str, str]] = set()
        self.findings: list[Finding] = []

    # -- with tracking -------------------------------------------------------

    def _locks_of(self, expr: ast.AST) -> set[tuple[str, str]]:
        if not isinstance(expr, ast.Attribute):
            return set()
        base_dump = ast.dump(expr.value)
        return {(base_dump, self.model.canonical(expr.attr))}

    def visit_With(self, node: ast.With) -> None:
        added = set()
        for item in node.items:
            added |= self._locks_of(item.context_expr) - self.held
        self.held |= added
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    visit_AsyncWith = visit_With

    # -- nested callables do not inherit the enclosing with ------------------

    def _visit_nested(self, node: ast.AST) -> None:
        saved = self.held
        self.held = set()
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.held = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # -- accesses ------------------------------------------------------------

    def _held_for(self, base_dump: str, lock: str) -> bool:
        return (base_dump, lock) in self.held or lock in self.method_holds

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = node.attr
        is_self = (
            isinstance(node.value, ast.Name) and node.value.id == "self"
        )
        lock: str | None = None
        if is_self:
            lock = self.model.guarded.get(attr)
            if lock is not None and self.is_init:
                lock = None  # construction: not yet shared
        elif attr in self.file_guarded:
            locks = self.file_guarded[attr]
            lock = next(iter(locks)) if len(locks) == 1 else None
            # ambiguous multi-class guards are skipped (scope the rule
            # rather than guess); single declarations check structurally
        if lock is not None and attr != lock:
            base_dump = ast.dump(node.value)
            if not self._held_for(base_dump, lock):
                self.findings.append(
                    Finding(
                        "C201",
                        self.ctx.path,
                        node.lineno,
                        f"attribute '{attr}' (guarded-by {lock}) accessed "
                        f"outside 'with {lock}'",
                    )
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.is_init:
            # Construction: the object is not yet shared, so helpers that
            # normally require the lock may run bare (e.g. replay/compact
            # before the lock even exists).
            self.generic_visit(node)
            return
        func = node.func
        callee: str | None = None
        base_dump: str | None = None
        if isinstance(func, ast.Attribute):
            callee = func.attr
            base_dump = ast.dump(func.value)
        elif isinstance(func, ast.Name):
            callee = func.id
        if callee is not None:
            required = self.model.holds.get(callee)
            if callee == self.model.node.name:
                required = self.model.holds.get("__init__")
                base_dump = None  # constructor: lock lives on another object
            if required:
                for lock in sorted(required):
                    if base_dump is not None:
                        ok = self._held_for(base_dump, lock)
                    else:
                        ok = (
                            any(h[1] == lock for h in self.held)
                            or lock in self.method_holds
                        )
                    if not ok:
                        self.findings.append(
                            Finding(
                                "C202",
                                self.ctx.path,
                                node.lineno,
                                f"call to '{callee}' (holds: {lock}) "
                                f"outside 'with {lock}'",
                            )
                        )
        self.generic_visit(node)


def check_guarded_by(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    models = [
        _collect_class(ctx, node)
        for node in ast.walk(ctx.tree)
        if isinstance(node, ast.ClassDef)
    ]
    # attr -> set of canonical lock names, across every class in the file
    # (cross-object accesses can't know the owning class statically)
    file_guarded: dict[str, set[str]] = {}
    for model in models:
        for attr, lock in model.guarded.items():
            file_guarded.setdefault(attr, set()).add(lock)

    for model in models:
        # C203: annotation hygiene — the named lock must exist
        for attr, lock in sorted(model.guarded.items()):
            if lock not in model.self_attrs:
                findings.append(
                    Finding(
                        "C203",
                        ctx.path,
                        model.node.lineno,
                        f"guarded-by on '{attr}' names unknown lock "
                        f"'{lock}' (no self.{lock} assignment in class "
                        f"{model.node.name})",
                    )
                )
        # (holds: locks are deliberately not validated against
        # self_attrs — the required lock may live on another object, as
        # with _PeerChannel.__init__ holding the transport's _lock.)
        for sub in model.node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                checker = _MethodChecker(ctx, model, file_guarded, sub)
                for stmt in sub.body:
                    checker.visit(stmt)
                findings.extend(checker.findings)
    return findings


register(
    Rule(
        id="C201",
        title="guarded attribute accessed without its lock",
        doc=(
            "Every load/store of a `# guarded-by: L` attribute must sit "
            "lexically inside `with <base>.L:` (Condition aliases count; "
            "__init__ is exempt; nested defs do not inherit the with)."
        ),
        check=check_guarded_by,
    )
)
register(
    Rule(
        id="C202",
        title="holds-annotated callee without the lock",
        doc=(
            "A `# holds: L` method requires L held by the caller; every "
            "call site must sit inside `with <base>.L:`.  Emitted by the "
            "C201 checker."
        ),
        check=None,
    )
)
register(
    Rule(
        id="C203",
        title="guarded-by/holds names an unknown lock",
        doc=(
            "The lock named by an annotation must be assigned as a self "
            "attribute in the class.  Emitted by the C201 checker."
        ),
        check=None,
    )
)
