"""Rule registry, suppressions, baseline, and runner.

Execution model: every ``*.py`` file under the requested paths is parsed
once into a :class:`FileContext`; per-file rules run over each context,
project rules (the D1xx auditor needs the whole import graph) run once
over the full context list.  Findings are then filtered through per-line
suppressions and the committed baseline.

Suppression convention (docs/ANALYSIS.md):

    something_flagged()  # lint: allow W7 <reason>

The reason is mandatory — a suppression without one is itself a finding
(rule S1: "a suppression without a reason is a finding").  Multiple ids
separate with commas: ``# lint: allow W7,C201 reason``.

Baseline: a committed JSON file mapping finding keys (path::rule::message
— deliberately line-number-free, so unrelated edits don't churn it) to
counts.  ``run`` masks up to that many matching findings, letting a new
rule land strict against new code without a big-bang cleanup; the gate
stays red for anything the baseline does not cover.  ``--update-baseline``
rewrites the file from the current findings.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\s+([A-Z]+\d*(?:\s*,\s*[A-Z]+\d*)*)\s*(.*)"
)

JSON_SCHEMA_VERSION = 1


@dataclass
class Finding:
    rule: str
    path: Path
    line: int
    message: str
    severity: str = "error"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def key(self, repo_root: Path | None = None) -> str:
        path = self.path
        if repo_root is not None:
            try:
                path = path.resolve().relative_to(repo_root.resolve())
            except ValueError:
                pass
        return f"{path.as_posix()}::{self.rule}::{self.message}"


@dataclass
class Rule:
    """One registered check.

    ``scope`` is a predicate over the file's resolved posix path (None =
    every file); ``check`` takes a FileContext and yields Findings.
    Rules with ``project=True`` instead receive the full list of
    contexts, once — the D1xx auditor builds its import graph there.
    """

    id: str
    title: str
    doc: str
    check: object  # callable; see class docstring
    scope: object = None  # callable(posix: str) -> bool, or None
    severity: str = "error"
    project: bool = False


class FileContext:
    """One parsed source file, shared by every per-file rule."""

    def __init__(self, path: Path):
        self.path = path
        self.posix = path.resolve().as_posix()
        self.src = path.read_text()
        self.lines = self.src.splitlines()
        self.syntax_error: SyntaxError | None = None
        try:
            self.tree: ast.Module | None = ast.parse(
                self.src, filename=str(path)
            )
        except SyntaxError as err:
            self.tree = None
            self.syntax_error = err
        # line -> (set of rule ids allowed, reason)
        self.suppressions: dict[int, tuple[set, str]] = {}
        for lineno, line in enumerate(self.lines, start=1):
            match = _SUPPRESS_RE.search(line)
            if match is not None:
                ids = {part.strip() for part in match.group(1).split(",")}
                self.suppressions[lineno] = (ids, match.group(2).strip())


REGISTRY: dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.id in REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    REGISTRY[rule.id] = rule
    return rule


def all_rules() -> list[Rule]:
    """Every registered rule, importing the rule modules on first use."""
    import importlib

    for mod in ("rules_c", "rules_d", "rules_w"):
        importlib.import_module(f".{mod}", __package__)
    return sorted(REGISTRY.values(), key=lambda r: r.id)


register(
    Rule(
        id="S1",
        title="suppression without a reason",
        doc=(
            "Every `# lint: allow <ID>` must carry a reason; a "
            "suppression without a reason is a finding."
        ),
        check=None,  # enforced inline by run(); registered for the catalog
    )
)


register(
    Rule(
        id="E0",
        title="syntax error",
        doc="The file does not parse; no other rule can run over it.",
        check=None,  # enforced inline by run(); registered for the catalog
    )
)


def _collect_contexts(paths: list[Path]) -> list[FileContext]:
    contexts = []
    for root in paths:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            contexts.append(FileContext(f))
    return contexts


def _apply_suppressions(
    contexts: list[FileContext], findings: list[Finding]
) -> list[Finding]:
    """Drop findings covered by a reasoned same-line suppression; emit S1
    findings for reason-less suppressions (and suppressions are never
    allowed to silence S1 itself)."""
    by_posix = {ctx.posix: ctx for ctx in contexts}
    out = []
    for finding in findings:
        ctx = by_posix.get(finding.path.resolve().as_posix())
        if ctx is not None:
            supp = ctx.suppressions.get(finding.line)
            if (
                supp is not None
                and finding.rule in supp[0]
                and supp[1]
                and finding.rule != "S1"
            ):
                continue
        out.append(finding)
    for ctx in contexts:
        for lineno, (ids, reason) in sorted(ctx.suppressions.items()):
            if not reason:
                out.append(
                    Finding(
                        rule="S1",
                        path=ctx.path,
                        line=lineno,
                        message=(
                            f"suppression of {','.join(sorted(ids))} "
                            "without a reason (a suppression without a "
                            "reason is a finding)"
                        ),
                    )
                )
    return out


@dataclass
class RunResult:
    findings: list[Finding] = field(default_factory=list)
    baselined: int = 0

    def render(self) -> list[str]:
        return [f.render() for f in self.findings]


def run(
    paths: list[Path],
    repo_root: Path | None = None,
    baseline: dict[str, int] | None = None,
) -> RunResult:
    """Run every registered rule over ``paths``; returns surviving
    findings (suppressions and baseline already applied) plus the count
    of baseline-masked ones."""
    rules = all_rules()
    contexts = _collect_contexts(paths)
    findings: list[Finding] = []
    for ctx in contexts:
        if ctx.syntax_error is not None:
            findings.append(
                Finding(
                    rule="E0",
                    path=ctx.path,
                    line=ctx.syntax_error.lineno or 1,
                    message=f"syntax error: {ctx.syntax_error.msg}",
                )
            )
            continue
        for rule in rules:
            if rule.check is None or rule.project:
                continue
            if rule.scope is not None and not rule.scope(ctx.posix):
                continue
            findings.extend(rule.check(ctx))
    parsed = [ctx for ctx in contexts if ctx.tree is not None]
    for rule in rules:
        if rule.check is None or not rule.project:
            continue
        findings.extend(rule.check(parsed))
    findings = _apply_suppressions(contexts, findings)

    result = RunResult()
    remaining = dict(baseline or {})
    for finding in findings:
        key = finding.key(repo_root)
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            result.baselined += 1
        else:
            result.findings.append(finding)
    result.findings.sort(key=lambda f: (str(f.path), f.line, f.rule))
    return result


# -- baseline ----------------------------------------------------------------


def load_baseline(path: Path) -> dict[str, int]:
    """Baseline file -> {finding key: masked count}.  Missing file = empty
    baseline (the strict default)."""
    try:
        doc = json.loads(path.read_text())
    except FileNotFoundError:
        return {}
    counts: dict[str, int] = {}
    for entry in doc.get("findings", []):
        counts[entry["key"]] = counts.get(entry["key"], 0) + int(
            entry.get("count", 1)
        )
    return counts


def dump_baseline(findings: list[Finding], repo_root: Path | None) -> dict:
    counts: dict[str, int] = {}
    for finding in findings:
        key = finding.key(repo_root)
        counts[key] = counts.get(key, 0) + 1
    return {
        "version": JSON_SCHEMA_VERSION,
        "comment": (
            "Accepted pre-existing findings, masked by tools/lint.py so "
            "new rules land strict against new code.  Shrink this file; "
            "never grow it (docs/ANALYSIS.md)."
        ),
        "findings": [
            {"key": key, "count": count}
            for key, count in sorted(counts.items())
        ],
    }


# -- machine-readable output -------------------------------------------------


def to_json(result: RunResult, repo_root: Path | None = None) -> dict:
    """The ``--json`` schema (round-trip-tested in tests/test_lint.py)."""
    counts: dict[str, int] = {}
    for finding in result.findings:
        counts[finding.rule] = counts.get(finding.rule, 0) + 1
    return {
        "version": JSON_SCHEMA_VERSION,
        "findings": [
            {
                "rule": f.rule,
                "severity": f.severity,
                "path": (
                    f.key(repo_root).split("::", 1)[0]
                    if repo_root is not None
                    else f.path.as_posix()
                ),
                "line": f.line,
                "message": f.message,
            }
            for f in result.findings
        ],
        "counts": counts,
        "baselined": result.baselined,
        "total": len(result.findings),
    }


def from_json(doc: dict) -> RunResult:
    """Inverse of :func:`to_json` (used by the schema round-trip test and
    by tooling that post-processes saved runs)."""
    if doc.get("version") != JSON_SCHEMA_VERSION:
        raise ValueError(f"unsupported schema version {doc.get('version')!r}")
    result = RunResult(baselined=int(doc.get("baselined", 0)))
    for entry in doc["findings"]:
        result.findings.append(
            Finding(
                rule=entry["rule"],
                path=Path(entry["path"]),
                line=int(entry["line"]),
                message=entry["message"],
                severity=entry.get("severity", "error"),
            )
        )
    return result
