"""Determinism purity auditor (D1xx).

The reference's correctness story rests on one invariant: the state
machine is a single-threaded, deterministic function of StateEvents that
never touches I/O, clocks, or randomness (Mir-BFT, arXiv:1906.05552;
the replayable-execution discipline inherited from PBFT).  This module
proves it *transitively*: it builds the module-level import graph over
``mirbft_tpu/`` and walks it from the purity roots —

- everything under ``mirbft_tpu/core/``
- the deterministic testengine paths: ``testengine/engine.py``,
  ``testengine/manglers.py``, ``testengine/certs.py``

— flagging every impure effect any reached module can perform:

- D101  impure stdlib import (clock, socket, threading, process, file
        or env I/O, OS entropy) reachable from a purity root
- D102  direct impure builtin call (``open``/``input``/``breakpoint``)
        in a pure module
- D103  ``id()`` in a pure module — an address-dependent value; anything
        derived from it diverges between the live run and a replay
- D104  iteration over a ``set`` in a pure module without a ``sorted()``
        wrap — str/bytes set order is PYTHONHASHSEED-dependent, so any
        ordered protocol state fed from it diverges across processes

Traversal stops at the sanctioned impurity boundaries (the Actions seam
analog): the telemetry switchboard ``mirbft_tpu.obsv.hooks`` — pure
modules may *record through* it, guarded by ``hooks.enabled``, but the
auditor neither follows its edges nor audits its body.  Third-party
imports (numpy/jax) are the accelerator substrate and are trusted;
``random`` is deliberately NOT impure here because W12 already bans
every unseeded spelling package-wide, so a surviving ``random`` use is a
seeded ``random.Random(seed)`` — deterministic by construction.

Per-module exemptions live in ``ALLOWLIST_IMPORTS`` with a mandatory
justification string, mirrored in docs/ANALYSIS.md.  Keep it short: an
allowlist entry is a documented hole in the proof.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding, Rule, register

# stdlib top-level module -> effect description.  Importing one of these
# from a pure module is D101 unless allowlisted.
IMPURE_MODULES: dict[str, str] = {
    "time": "wall clock / timers",
    "datetime": "wall clock",
    "socket": "socket I/O",
    "select": "socket I/O",
    "selectors": "socket I/O",
    "ssl": "socket I/O",
    "http": "socket I/O",
    "urllib": "socket I/O",
    "asyncio": "event loop / socket I/O",
    "threading": "threads",
    "queue": "thread synchronization",
    "concurrent": "thread/process pools",
    "subprocess": "process control",
    "multiprocessing": "process control",
    "signal": "process control",
    "os": "file/env I/O",
    "sys": "interpreter/environment state",
    "pathlib": "file I/O surface",
    "shutil": "file I/O",
    "tempfile": "file I/O",
    "glob": "file I/O",
    "fileinput": "file I/O",
    "secrets": "OS entropy",
    "uuid": "OS entropy / host identity",
}

# Sanctioned impurity boundaries: edges into these modules are allowed
# and traversal stops there.  hooks is the telemetry switchboard every
# instrumented module records through (guarded by ``hooks.enabled``);
# it is the Python port's analog of the reference's Actions seam — the
# one doorway through which the pure world touches the impure one.
# device is the same seam for the kernel layer: ops/ entry points time
# themselves through it, and the wrapper is a passthrough (one module
# load and a branch) unless a capture registry is installed.
# core.device_tracker is the protocol's one sanctioned accelerator
# boundary (the device-resident ack plane, lint rule W16): the tracker
# reaches it only behind Config.ack_plane, its kernels are replay-
# deterministic by contract, and the shadow oracle audits that contract
# — so traversal stops at its edge rather than dragging jax into the
# purity proof.
BOUNDARY_MODULES = frozenset(
    {
        "mirbft_tpu.obsv.hooks",
        "mirbft_tpu.obsv.device",
        "mirbft_tpu.core.device_tracker",
    }
)

# module -> {stdlib top-level name: justification}.  Mirrored in
# docs/ANALYSIS.md; every entry is a documented hole in the proof.
ALLOWLIST_IMPORTS: dict[str, dict[str, str]] = {
    "mirbft_tpu.core.state_machine": {
        "time": (
            "time.perf_counter telemetry behind hooks.enabled only; the "
            "event-handling contract itself stays clock-free"
        ),
    },
}

DETERMINISTIC_TESTENGINE = frozenset(
    {
        "mirbft_tpu.testengine.engine",
        "mirbft_tpu.testengine.manglers",
        "mirbft_tpu.testengine.certs",
    }
)

_IMPURE_BUILTINS = ("open", "input", "breakpoint", "exec", "eval")


def module_name(posix: str) -> str | None:
    """Resolved posix path -> dotted module name, or None for files
    outside a ``mirbft_tpu/`` tree.  Fragment-based so synthetic trees
    under tmp_path audit exactly like the real package."""
    idx = posix.rfind("mirbft_tpu/")
    if idx < 0 or not posix.endswith(".py"):
        return None
    name = posix[idx:-3].replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def is_purity_root(name: str) -> bool:
    return (
        name == "mirbft_tpu.core"
        or name.startswith("mirbft_tpu.core.")
        or name in DETERMINISTIC_TESTENGINE
    )


class _ModuleInfo:
    def __init__(self, name: str, ctx: FileContext, is_package: bool):
        self.name = name
        self.ctx = ctx
        # Anchor for level-1 relative imports: the package itself for
        # __init__.py, the containing package for regular modules.
        self.package = name if is_package else name.rsplit(".", 1)[0]


def _edges_and_imports(
    info: _ModuleInfo, project: set[str]
) -> tuple[set[str], list[tuple[int, str]]]:
    """(intra-package edges, [(line, impure top-level stdlib name)]).

    Function-level imports count too — a lazy ``import time`` inside a
    handler is exactly the effect the audit exists to catch."""
    edges: set[str] = set()
    external: list[tuple[int, str]] = []

    def _external(lineno: int, dotted: str) -> None:
        top = dotted.split(".")[0]
        if top in IMPURE_MODULES:
            external.append((lineno, top))

    for node in ast.walk(info.ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in project:
                    edges.add(alias.name)
                else:
                    _external(node.lineno, alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                resolved = node.module or ""
            else:
                parts = info.package.split(".")
                parts = parts[: len(parts) - (node.level - 1)]
                if node.module:
                    parts.append(node.module)
                resolved = ".".join(parts)
            if not resolved:
                continue
            if resolved == "__future__":
                continue
            for alias in node.names:
                candidate = f"{resolved}.{alias.name}"
                if candidate in project:
                    edges.add(candidate)
                elif resolved in project:
                    edges.add(resolved)
                else:
                    _external(node.lineno, resolved)
    return edges, external


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in ("set", "frozenset")
    )


def _direct_effects(ctx: FileContext) -> list[Finding]:
    """D102/D103/D104 findings for one pure module's own body."""
    out: list[Finding] = []
    # Iteration sites that are arguments of sorted(...) are sanctioned.
    sorted_args = {
        id(arg)
        for n in ast.walk(ctx.tree)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Name)
        and n.func.id == "sorted"
        for arg in n.args
    }
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id in _IMPURE_BUILTINS:
                out.append(
                    Finding(
                        "D102",
                        ctx.path,
                        node.lineno,
                        f"impure builtin {node.func.id}() in a pure module",
                    )
                )
            elif node.func.id == "id" and node.args:
                out.append(
                    Finding(
                        "D103",
                        ctx.path,
                        node.lineno,
                        "id() in a pure module (address-dependent value "
                        "diverges between live run and replay)",
                    )
                )
            elif (
                node.func.id in ("list", "tuple", "enumerate", "iter")
                and node.args
                and _is_set_expr(node.args[0])
            ):
                out.append(
                    Finding(
                        "D104",
                        ctx.path,
                        node.lineno,
                        f"{node.func.id}() over a set in a pure module "
                        "(hash-seed-dependent order; wrap in sorted())",
                    )
                )
        iters: list[ast.AST] = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.DictComp)):
            iters.extend(gen.iter for gen in node.generators)
        for it in iters:
            if _is_set_expr(it) and id(it) not in sorted_args:
                out.append(
                    Finding(
                        "D104",
                        ctx.path,
                        it.lineno,
                        "iteration over a set in a pure module "
                        "(hash-seed-dependent order; wrap in sorted())",
                    )
                )
    return out


def check_purity(contexts: list[FileContext]) -> list[Finding]:
    modules: dict[str, _ModuleInfo] = {}
    for ctx in contexts:
        name = module_name(ctx.posix)
        if name is not None:
            modules[name] = _ModuleInfo(
                name, ctx, ctx.posix.endswith("/__init__.py")
            )

    project = set(modules)
    graph: dict[str, set[str]] = {}
    external: dict[str, list[tuple[int, str]]] = {}
    for name, info in modules.items():
        graph[name], external[name] = _edges_and_imports(info, project)

    roots = sorted(n for n in modules if is_purity_root(n))
    # name -> import chain from the first root that reached it.
    chain: dict[str, tuple[str, ...]] = {}
    queue: list[str] = []
    for root in roots:
        if root not in chain:
            chain[root] = (root,)
            queue.append(root)
    while queue:
        current = queue.pop(0)
        if current in BOUNDARY_MODULES:
            continue
        for dep in sorted(graph.get(current, ())):
            if dep not in chain:
                chain[dep] = chain[current] + (dep,)
                queue.append(dep)

    findings: list[Finding] = []
    seen: set[tuple[str, int, str]] = set()
    for name in sorted(chain):
        if name in BOUNDARY_MODULES:
            continue
        info = modules[name]
        via = " -> ".join(chain[name])
        allowed = ALLOWLIST_IMPORTS.get(name, {})
        for lineno, top in external.get(name, []):
            if top in allowed:
                continue
            key = (info.ctx.posix, lineno, top)
            if key in seen:
                continue
            seen.add(key)
            findings.append(
                Finding(
                    "D101",
                    info.ctx.path,
                    lineno,
                    f"impure import '{top}' ({IMPURE_MODULES[top]}) "
                    f"reachable from purity root (via {via})",
                )
            )
        for finding in _direct_effects(info.ctx):
            key = (info.ctx.posix, finding.line, finding.rule)
            if key in seen:
                continue
            seen.add(key)
            finding.message += f" (via {via})"
            findings.append(finding)
    return findings


register(
    Rule(
        id="D101",
        title="impure import reachable from a purity root",
        doc=(
            "core/ and the deterministic testengine must never "
            "transitively import clocks, sockets, threads, processes, "
            "file/env I/O, or OS entropy; exemptions live in "
            "ALLOWLIST_IMPORTS with a justification."
        ),
        check=check_purity,
        project=True,
    )
)
register(
    Rule(
        id="D102",
        title="impure builtin call in a pure module",
        doc=(
            "open()/input()/breakpoint()/exec()/eval() in a module "
            "reachable from a purity root.  Emitted by the D101 "
            "traversal."
        ),
        check=None,
    )
)
register(
    Rule(
        id="D103",
        title="id() in a pure module",
        doc=(
            "id() yields an address-dependent value; anything derived "
            "from it diverges between the live run and a replay.  "
            "Emitted by the D101 traversal."
        ),
        check=None,
    )
)
register(
    Rule(
        id="D104",
        title="set iteration in a pure module",
        doc=(
            "str/bytes set iteration order is PYTHONHASHSEED-dependent; "
            "ordered protocol state fed from it diverges across "
            "processes.  Wrap the set in sorted().  Emitted by the D101 "
            "traversal."
        ),
        check=None,
    )
)
