"""CLI for the analysis suite (invoked through the tools/lint.py shim).

    python tools/lint.py [paths...]            human-readable findings
    python tools/lint.py --json [paths...]     machine-readable (schema v1)
    python tools/lint.py --update-baseline     accept current findings

Exit status: 0 when no non-baselined findings, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

from .engine import dump_baseline, load_baseline, run, to_json

REPO = Path(__file__).resolve().parent.parent.parent
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

DEFAULT_TARGETS = (
    "mirbft_tpu",
    "tests",
    "tools",
    "bench.py",
    "__graft_entry__.py",
)


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="tools/lint.py", description=__doc__
    )
    parser.add_argument("paths", nargs="*", type=Path)
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the machine-readable schema instead of text",
    )
    parser.add_argument(
        "--baseline", type=Path, default=BASELINE_PATH,
        help="baseline file masking accepted findings",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    args = parser.parse_args(argv)

    targets = args.paths or [REPO / t for t in DEFAULT_TARGETS]

    if args.update_baseline:
        result = run(targets, repo_root=REPO, baseline=None)
        args.baseline.write_text(
            json.dumps(dump_baseline(result.findings, REPO), indent=2) + "\n"
        )
        print(
            f"lint: baseline updated with {len(result.findings)} finding(s)"
        )
        return 0

    baseline = load_baseline(args.baseline)
    result = run(targets, repo_root=REPO, baseline=baseline)
    if args.as_json:
        print(json.dumps(to_json(result, REPO), indent=2))
    else:
        for line in result.render():
            print(line)
        print(f"lint: {len(result.findings)} finding(s)")
        if result.baselined:
            print(f"lint: {result.baselined} baselined finding(s) masked")
    return 1 if result.findings else 0
